"""Multi-host training end-to-end: a LocalRunner-launched 2-process
`jax.distributed` cluster actually TRAINS (not just allgathers), and the
result equals the single-process run — for the reference trainer API (ADAG)
AND the beyond-reference GSPMD trainer (MeshTrainer/FSDP); plus the socket
PS serving workers across a real process boundary.

Parity: the reference really trained across machines (reference
``distkeras/workers.py :: Worker.train`` ran on remote Spark executors;
``distkeras/job_deployment.py :: Job`` submitted to a live cluster —
SURVEY.md §3.1 boundaries #1/#2). Here the same programs run
multi-controller SPMD: every process feeds `put_global` the same
deterministic batches and XLA runs one global program over the 2-host mesh.
"""

import json
import os
import socket
import textwrap

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# shared recipes so oracle and cluster cannot drift apart
ADAG_SNIPPET = """
from distkeras_tpu import ADAG
from distkeras_tpu.datasets import higgs
from distkeras_tpu.models import mlp
import jax.numpy as jnp

def run_training():
    train, _ = higgs(n_train=2048, n_test=64)
    t = ADAG(mlp(input_shape=(28,), hidden=(32, 16), num_classes=2,
                 dtype=jnp.float32),
             loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
             learning_rate=0.05, num_workers=8, batch_size=16,
             communication_window=2, num_epoch=2, seed=7,
             device_data=False)
    params = t.train(train, shuffle=True)
    losses = [float(l) for l in t.get_history().losses()]
    return params, losses
"""

MESH_SNIPPET = """
from distkeras_tpu.datasets import higgs
from distkeras_tpu.models import mlp
from distkeras_tpu.trainers import MeshTrainer
import jax.numpy as jnp

def run_training():
    train, _ = higgs(n_train=512, n_test=64)
    t = MeshTrainer(
        mlp(input_shape=(28,), hidden=(64, 32), num_classes=2,
            dtype=jnp.float32),
        loss="sparse_softmax_cross_entropy", worker_optimizer="adam",
        learning_rate=1e-3, mesh_shape={"dp": 8},
        parameter_sharding="fsdp", batch_size=32, num_epoch=2, seed=11,
        input_mode="stream",
    )
    params = t.train(train)
    losses = [float(l) for l in t.get_history().losses()]
    return params, losses
"""


def run_two_process_cluster_vs_oracle(tmp_path, train_snippet,
                                      timeout=420):
    """Launch `train_snippet.run_training()` on a LocalRunner 2-process
    `jax.distributed` cluster (4+4 virtual CPU devices), run the same
    recipe single-process as the oracle, and assert params AND losses
    match. The snippet must define ``run_training() -> (params, losses)``.
    """
    from distkeras_tpu.job_deployment import Job, LocalRunner, Punchcard

    with socket.socket() as s:  # free coordinator port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(f"""
        import json, os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        sys.path.insert(0, {str(REPO)!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        from distkeras_tpu.job_deployment import (
            cluster_args_from_env, initialize_cluster)
        info = initialize_cluster(**cluster_args_from_env())
        assert info["process_count"] == 2, info
        assert len(jax.devices()) == 8, jax.devices()
    """) + train_snippet + textwrap.dedent(f"""
        import numpy as np
        params, losses = run_training()
        if jax.process_index() == 0:
            leaves = jax.tree.leaves(params)
            np.savez({str(tmp_path)!r} + "/params.npz",
                     **{{str(i): np.asarray(l) for i, l in enumerate(leaves)}})
            with open({str(tmp_path)!r} + "/losses.json", "w") as f:
                json.dump(losses, f)
    """))

    pc = Punchcard(script=str(worker), hosts=["localhost", "localhost"],
                   coordinator_port=port)
    runner = LocalRunner()
    Job(pc, runner=runner).run()
    codes = runner.wait(timeout=timeout)
    assert codes == [0, 0], [p.captured_stderr[-2000:] for p in runner.procs]

    # the single-process oracle: same recipe on this process's 8-device mesh
    ns = {}
    exec(train_snippet, ns)
    oracle_params, oracle_losses = ns["run_training"]()
    oracle_leaves = jax.tree.leaves(oracle_params)

    got = np.load(tmp_path / "params.npz")
    assert len(got.files) == len(oracle_leaves)
    for i, leaf in enumerate(oracle_leaves):
        np.testing.assert_allclose(
            got[str(i)], np.asarray(leaf), rtol=1e-5, atol=1e-6,
            err_msg=f"leaf {i} diverged between 1-process and 2-process runs",
        )

    cluster_losses = json.loads((tmp_path / "losses.json").read_text())
    np.testing.assert_allclose(cluster_losses, oracle_losses,
                               rtol=1e-4, atol=1e-5)
    assert cluster_losses[-1] < cluster_losses[0]  # it actually learned


@pytest.mark.slow
def test_two_process_adag_matches_single_process(tmp_path):
    run_two_process_cluster_vs_oracle(tmp_path, ADAG_SNIPPET)


@pytest.mark.slow
def test_two_process_mesh_trainer_fsdp_matches_single_process(tmp_path):
    """The GSPMD path trains across processes too: a 2-process MeshTrainer
    (ZeRO-3 params + moments sharded over an 8-device dp axis spanning both
    controllers, final params gathered via process_allgather) matches the
    single-process run."""
    run_two_process_cluster_vs_oracle(tmp_path, MESH_SNIPPET)


@pytest.mark.slow
@pytest.mark.parametrize("transport", ["socket", "native"])
def test_cross_process_ps_downpour(tmp_path, transport):
    """The socket/native PS really serves REMOTE workers: two LocalRunner
    worker processes train DOWNPOUR over TCP against a PS in THIS process
    (the reference's driver-hosted PS serving Spark executors — reference
    ``distkeras/parameter_servers.py :: SocketParameterServer``). Pins the
    DCN/multi-slice claim: every pull/commit crosses a process boundary —
    for both the Python pickle wire and the C++ flat-f32 wire.
    """
    import jax.numpy as jnp

    from distkeras_tpu.job_deployment import Job, LocalRunner, Punchcard
    from distkeras_tpu.models import mlp
    from distkeras_tpu.parallel.merge_rules import DownpourMerge
    from distkeras_tpu.parameter_servers import SocketParameterServer

    W_PER, N_PROC, WINDOW, BATCH, ROWS = 2, 2, 2, 16, 128
    spec = mlp(input_shape=(28,), hidden=(32,), num_classes=2,
               dtype=jnp.float32)
    params0, _ = spec.init_np(7)
    if transport == "native":
        from distkeras_tpu.native import load_dkps
        from distkeras_tpu.native_ps import NativeSocketParameterServer

        if load_dkps() is None:
            pytest.skip("no C++ toolchain to build libdkps")
        ps = NativeSocketParameterServer(
            params0, DownpourMerge(), W_PER * N_PROC, host="127.0.0.1"
        )
    else:
        ps = SocketParameterServer(
            params0, DownpourMerge(), W_PER * N_PROC, host="127.0.0.1"
        )
    ps.initialize()
    ps.start()
    try:
        worker = tmp_path / "ps_worker.py"
        worker.write_text(textwrap.dedent(f"""
            import json, os, sys
            os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
            sys.path.insert(0, {str(REPO)!r})
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            from distkeras_tpu import DOWNPOUR
            from distkeras_tpu.datasets import higgs
            from distkeras_tpu.models import mlp

            pid = int(os.environ["DISTKERAS_PROCESS_ID"])
            train, _ = higgs(n_train={ROWS * N_PROC}, n_test=64)
            lo = pid * {ROWS}
            shard = train.select(["features", "label"])
            shard = type(shard)({{c: shard[c][lo : lo + {ROWS}]
                                 for c in shard.columns}})
            t = DOWNPOUR(
                mlp(input_shape=(28,), hidden=(32,), num_classes=2,
                    dtype=jnp.float32),
                loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
                learning_rate=0.05, num_workers={W_PER}, batch_size={BATCH},
                communication_window={WINDOW}, num_epoch=1, seed=7 + pid,
                backend="ps", ps_transport={transport!r}, ps_host="127.0.0.1",
                ps_port=int(os.environ["DK_PS_PORT"]),
                worker_id_offset=pid * {W_PER},
            )
            t.train(shard)
            losses = [float(l) for l in t.get_history().losses()]
            with open({str(tmp_path)!r} + f"/losses_{{pid}}.json", "w") as f:
                json.dump(losses, f)
        """))
        pc = Punchcard(script=str(worker),
                       hosts=["localhost"] * N_PROC,
                       env={"DK_PS_PORT": str(ps.port)})
        runner = LocalRunner()
        Job(pc, runner=runner).run()
        codes = runner.wait(timeout=300)
        assert codes == [0] * N_PROC, \
            [p.captured_stderr[-2000:] for p in runner.procs]

        # every worker in every process committed exactly its window count
        windows_per_worker = (ROWS // W_PER) // (WINDOW * BATCH)
        assert ps.num_updates == W_PER * N_PROC * windows_per_worker

        for pid in range(N_PROC):
            losses = json.loads(
                (tmp_path / f"losses_{pid}.json").read_text()
            )
            assert len(losses) == W_PER * windows_per_worker
            assert np.isfinite(losses).all()

        # the center actually moved off its initialization
        center = ps.get_model()
        diffs = [
            float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for a, b in zip(jax.tree.leaves(center), jax.tree.leaves(params0))
        ]
        assert max(diffs) > 0
    finally:
        ps.stop()


@pytest.mark.slow
def test_two_process_checkpoint_resume_matches_uninterrupted(tmp_path):
    """Process-sharded checkpointing under a REAL 2-process cluster: a
    MeshTrainer/FSDP run checkpoints its ZeRO-sharded state (each
    controller writes only its own shards), a fresh trainer resumes from
    epoch 2, and the resumed final params equal the uninterrupted
    single-process 4-epoch oracle."""
    from distkeras_tpu.job_deployment import Job, LocalRunner, Punchcard

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    ckdir = tmp_path / "ckpts"
    recipe = f"""
from distkeras_tpu.datasets import higgs
from distkeras_tpu.models import mlp
from distkeras_tpu.trainers import MeshTrainer
import jax.numpy as jnp

def make_trainer(num_epoch, resume):
    return MeshTrainer(
        mlp(input_shape=(28,), hidden=(64, 32), num_classes=2,
            dtype=jnp.float32),
        loss="sparse_softmax_cross_entropy", worker_optimizer="adam",
        learning_rate=1e-3, mesh_shape={{"dp": 8}},
        parameter_sharding="fsdp", batch_size=32, num_epoch=num_epoch,
        seed=11, input_mode="stream",
        checkpoint_dir={str(ckdir)!r}, resume=resume,
    )

def data():
    return higgs(n_train=512, n_test=64)[0]
"""

    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(f"""
        import json, os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        sys.path.insert(0, {str(REPO)!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        from distkeras_tpu.job_deployment import (
            cluster_args_from_env, initialize_cluster)
        initialize_cluster(**cluster_args_from_env())
    """) + recipe + textwrap.dedent(f"""
        import numpy as np
        make_trainer(2, resume=False).train(data())   # epochs 0-1 + ckpt
        t = make_trainer(4, resume=True)              # resumes at epoch 2
        params = t.train(data())
        if jax.process_index() == 0:
            leaves = jax.tree.leaves(params)
            np.savez({str(tmp_path)!r} + "/params.npz",
                     **{{str(i): np.asarray(l) for i, l in enumerate(leaves)}})
    """))

    pc = Punchcard(script=str(worker), hosts=["localhost", "localhost"],
                   coordinator_port=port)
    runner = LocalRunner()
    Job(pc, runner=runner).run()
    codes = runner.wait(timeout=420)
    assert codes == [0, 0], [p.captured_stderr[-2000:] for p in runner.procs]

    # oracle: the same recipe, 4 uninterrupted epochs, this process's mesh
    ns = {}
    exec(recipe.replace(repr(str(ckdir)), "None"), ns)
    oracle = ns["make_trainer"](4, resume=False).train(ns["data"]())
    oracle_leaves = jax.tree.leaves(oracle)

    got = np.load(tmp_path / "params.npz")
    assert len(got.files) == len(oracle_leaves)
    for i, leaf in enumerate(oracle_leaves):
        np.testing.assert_allclose(
            got[str(i)], np.asarray(leaf), rtol=1e-5, atol=1e-6,
            err_msg=f"leaf {i}: resumed 2-process != uninterrupted oracle",
        )
    # and the checkpoint dir really is process-sharded: files from 2 procs
    shard_files = list(ckdir.glob("*.dks"))
    assert any("p00000of00002" in f.name for f in shard_files)
    assert any("p00001of00002" in f.name for f in shard_files)


@pytest.mark.slow
def test_two_process_adag_checkpoint_resume(tmp_path):
    """The COLLECTIVE backend's checkpoint path under a real 2-process
    cluster: ADAG snapshots its stacked-worker TrainState process-sharded,
    a fresh trainer resumes mid-run, and the result equals the
    uninterrupted single-process oracle (same worker count, so the exact
    — not elastic — resume path runs)."""
    from distkeras_tpu.job_deployment import Job, LocalRunner, Punchcard

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    ckdir = tmp_path / "ckpts"
    recipe = f"""
from distkeras_tpu import ADAG
from distkeras_tpu.datasets import higgs
from distkeras_tpu.models import mlp
import jax.numpy as jnp

def make_trainer(num_epoch, resume):
    return ADAG(
        mlp(input_shape=(28,), hidden=(32, 16), num_classes=2,
            dtype=jnp.float32),
        loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
        learning_rate=0.05, num_workers=8, batch_size=16,
        communication_window=2, num_epoch=num_epoch, seed=7,
        device_data=False,
        checkpoint_dir={str(ckdir)!r}, resume=resume,
    )

def data():
    return higgs(n_train=2048, n_test=64)[0]
"""

    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(f"""
        import json, os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        sys.path.insert(0, {str(REPO)!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        from distkeras_tpu.job_deployment import (
            cluster_args_from_env, initialize_cluster)
        initialize_cluster(**cluster_args_from_env())
    """) + recipe + textwrap.dedent(f"""
        import numpy as np
        make_trainer(2, resume=False).train(data())   # epochs 0-1 + ckpt
        params = make_trainer(4, resume=True).train(data())
        if jax.process_index() == 0:
            leaves = jax.tree.leaves(params)
            np.savez({str(tmp_path)!r} + "/params.npz",
                     **{{str(i): np.asarray(l) for i, l in enumerate(leaves)}})
    """))

    pc = Punchcard(script=str(worker), hosts=["localhost", "localhost"],
                   coordinator_port=port)
    runner = LocalRunner()
    Job(pc, runner=runner).run()
    codes = runner.wait(timeout=420)
    assert codes == [0, 0], [p.captured_stderr[-2000:] for p in runner.procs]

    ns = {}
    exec(recipe.replace(repr(str(ckdir)), "None"), ns)
    oracle = ns["make_trainer"](4, resume=False).train(ns["data"]())
    oracle_leaves = jax.tree.leaves(oracle)

    got = np.load(tmp_path / "params.npz")
    assert len(got.files) == len(oracle_leaves)
    for i, leaf in enumerate(oracle_leaves):
        np.testing.assert_allclose(
            got[str(i)], np.asarray(leaf), rtol=1e-5, atol=1e-6,
            err_msg=f"leaf {i}: resumed ADAG != uninterrupted oracle",
        )


VAL_RECIPE = """
from distkeras_tpu import ADAG
from distkeras_tpu.datasets import higgs
from distkeras_tpu.models import mlp
from distkeras_tpu.trainers import MeshTrainer
import jax.numpy as jnp

def _model():
    return mlp(input_shape=(28,), hidden=(32, 16), num_classes=2,
               dtype=jnp.float32)

def run_mesh(profile_dir):
    import jax as _jax
    import numpy as _np

    train, test = higgs(n_train=512, n_test=90)
    t = MeshTrainer(_model(), loss="sparse_softmax_cross_entropy",
                    worker_optimizer="adam", learning_rate=1e-3,
                    mesh_shape={"dp": 8}, parameter_sharding="fsdp",
                    batch_size=32, num_epoch=2, seed=11,
                    input_mode="stream", validation_data=test,
                    profile_dir=profile_dir, ema_decay=0.5)
    t.train(train)
    recs = [[r["epoch"], r["val_loss"], r.get("val_accuracy")]
            for r in t.metrics_ if "val_loss" in r]
    # per-leaf position-weighted EMA checksums: pins the cross-process EMA
    # gather (ZeRO-sharded carries process_allgather'd + re-laid-out)
    # against the oracle — position weights catch shard-order scrambles a
    # plain sum would miss
    assert t.ema_params_ is not None
    recs.append([
        float(_np.dot(_np.asarray(l, _np.float64).ravel(),
                      _np.arange(1, l.size + 1, dtype=_np.float64)))
        for l in _jax.tree.leaves(t.ema_params_)
    ])
    return recs

def run_adag():
    train, test = higgs(n_train=1024, n_test=90)
    t = ADAG(_model(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="sgd", learning_rate=0.05, num_workers=8,
             batch_size=16, communication_window=2, num_epoch=2, seed=7,
             device_data=False, validation_data=test)
    t.train(train)
    return [[r["epoch"], r["val_loss"], r.get("val_accuracy")]
            for r in t.metrics_ if "val_loss" in r]
"""


@pytest.mark.slow
def test_two_process_validation_and_profile(tmp_path):
    """validation_data + profile_dir under a REAL 2-process cluster — the
    two aux features that used to raise NotImplementedError multi-process.
    The per-epoch val_loss/val_accuracy scored on globally-sharded params
    (FSDP MeshTrainer and ADAG's stacked-worker center — eval batches enter
    as replicated global arrays via put_global) equal the single-process
    oracle's, and each controller writes its own profiler trace
    subdirectory (``process{i}/``). The 90-row validation split does not
    divide either batch size, so the padded-chunk mask path runs too."""
    from distkeras_tpu.job_deployment import Job, LocalRunner, Punchcard

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    trace_dir = tmp_path / "trace"
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(f"""
        import json, os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        sys.path.insert(0, {str(REPO)!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        from distkeras_tpu.job_deployment import (
            cluster_args_from_env, initialize_cluster)
        initialize_cluster(**cluster_args_from_env())
    """) + VAL_RECIPE + textwrap.dedent(f"""
        mesh_val = run_mesh({str(trace_dir)!r})
        adag_val = run_adag()
        if jax.process_index() == 0:
            with open({str(tmp_path)!r} + "/val.json", "w") as f:
                json.dump({{"mesh": mesh_val, "adag": adag_val}}, f)
    """))

    pc = Punchcard(script=str(worker), hosts=["localhost", "localhost"],
                   coordinator_port=port)
    runner = LocalRunner()
    Job(pc, runner=runner).run()
    codes = runner.wait(timeout=420)
    assert codes == [0, 0], [p.captured_stderr[-2000:] for p in runner.procs]

    ns = {}
    exec(VAL_RECIPE, ns)
    oracle = {"mesh": ns["run_mesh"](None), "adag": ns["run_adag"]()}

    got = json.loads((tmp_path / "val.json").read_text())
    # mesh yields 2 val records + a trailing per-leaf EMA-sum row; adag
    # yields the 2 val records only
    assert len(got["mesh"]) == 3 and len(got["adag"]) == 2, got
    for key in ("mesh", "adag"):
        for (ep_c, vl_c, va_c), (ep_o, vl_o, va_o) in zip(got[key][:2],
                                                          oracle[key][:2]):
            assert ep_c == ep_o
            np.testing.assert_allclose(vl_c, vl_o, rtol=1e-4, atol=1e-5,
                                       err_msg=f"{key} val_loss diverged")
            np.testing.assert_allclose(va_c, va_o, rtol=1e-4, atol=1e-5,
                                       err_msg=f"{key} val_accuracy diverged")
    assert len(got["mesh"][2]) == 6  # the mlp's 3 Dense layers x (W, b)
    np.testing.assert_allclose(
        got["mesh"][2], oracle["mesh"][2], rtol=1e-4, atol=1e-5,
        err_msg="cross-process EMA diverged from the single-process oracle",
    )

    # per-process profiler traces: one subdirectory per controller, each
    # with a non-empty trace session inside
    for pid in (0, 1):
        sub = trace_dir / f"process{pid}"
        assert sub.is_dir(), f"missing trace dir for process {pid}"
        assert any(sub.rglob("*")), f"empty trace dir for process {pid}"


@pytest.mark.slow
def test_two_process_ps_backend_through_trainer_api(tmp_path):
    """backend='ps' under a REAL 2-process jax.distributed cluster, through
    plain trainer.train(ds): process 0 hosts the PS automatically, each
    controller runs its 2 local hogwild workers against it over TCP with
    offset ids, and the post-barrier pull hands BOTH controllers the same
    trained center (checksums allgathered and compared in-cluster)."""
    from distkeras_tpu.job_deployment import Job, LocalRunner, Punchcard

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(f"""
        import json, os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        sys.path.insert(0, {str(REPO)!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        from distkeras_tpu.job_deployment import (
            cluster_args_from_env, initialize_cluster)
        initialize_cluster(**cluster_args_from_env())
        import numpy as np
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        from distkeras_tpu import DOWNPOUR
        from distkeras_tpu.datasets import higgs
        from distkeras_tpu.models import mlp

        from distkeras_tpu.data import Dataset

        train, _ = higgs(n_train=2048, n_test=64)
        # LABEL-SORTED rows: the strided per-process split must still hand
        # every controller all classes (a contiguous cut would give each
        # controller one class and wreck the center)
        order = np.argsort(train["label"], kind="stable")
        train = Dataset({{c: train[c][order] for c in train.columns}})
        t = DOWNPOUR(
            mlp(input_shape=(28,), hidden=(32, 16), num_classes=2,
                dtype=jnp.float32),
            loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
            learning_rate=0.02, num_workers=4, batch_size=16,
            communication_window=2, num_epoch=2, seed=3, backend="ps",
        )
        params = t.train(train, shuffle=True)
        losses = [float(l) for l in t.get_history().losses()]
        assert np.isfinite(losses).all(), losses
        # 2 local workers x 16 windows x 2 epochs of per-window records
        assert len(losses) == 64, len(losses)
        # every controller ends with the identical center
        ck = np.asarray([
            float(np.dot(np.asarray(l, np.float64).ravel(),
                         np.arange(1, np.asarray(l).size + 1,
                                   dtype=np.float64)))
            for l in jax.tree.leaves(params)
        ])
        everyone = np.asarray(multihost_utils.process_allgather(ck))
        np.testing.assert_allclose(everyone[0], everyone[1], rtol=1e-9,
                                   err_msg="controllers returned "
                                           "different centers")
        if jax.process_index() == 0:
            with open({str(tmp_path)!r} + "/losses.json", "w") as f:
                json.dump(losses, f)
    """))

    pc = Punchcard(script=str(worker), hosts=["localhost", "localhost"],
                   coordinator_port=port)
    runner = LocalRunner()
    Job(pc, runner=runner).run()
    codes = runner.wait(timeout=420)
    assert codes == [0, 0], [p.captured_stderr[-2000:] for p in runner.procs]

    losses = json.loads((tmp_path / "losses.json").read_text())
    assert np.mean(losses[-8:]) < losses[0]  # it actually learned
