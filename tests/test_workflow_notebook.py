"""Execute examples/workflow.ipynb's code cells on the 8-device CPU mesh.

The reference's second canonical example (`workflow.ipynb`, ATLAS Higgs —
SURVEY.md §2b #19) must run top-to-bottom and clear 0.70 test accuracy; its
final cell asserts that itself, so plain execution is the test.
"""

import os
import pathlib

import nbformat
import pytest


@pytest.mark.slow
def test_workflow_notebook_executes_end_to_end(monkeypatch):
    monkeypatch.setenv("DISTKERAS_WORKFLOW_ROWS", "8192")
    path = pathlib.Path(__file__).parent.parent / "examples" / "workflow.ipynb"
    nb = nbformat.read(path, as_version=4)
    ns: dict = {}
    monkeypatch.chdir(path.parent)
    for cell in nb.cells:
        if cell.cell_type == "code":
            exec(compile(cell.source, str(path), "exec"), ns)
    # the notebook's own bar, re-asserted here for a readable failure
    assert all(acc > 0.70 for acc in ns["results"].values()), ns["results"]
