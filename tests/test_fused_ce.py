"""Chunked fused linear+cross-entropy (``ops/fused_ce.py``).

Oracle: the unfused path — materialize ``hidden @ kernel + bias`` and take
``sparse_softmax_cross_entropy`` (masked form when a mask is given). The
fused op must match it in value AND in the gradients w.r.t. hidden, kernel,
and bias, across chunk sizes that do and don't divide the row count.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops import losses
from distkeras_tpu.ops.fused_ce import chunked_softmax_cross_entropy


def _oracle(hidden, labels, kernel, bias, mask=None):
    logits = (
        jnp.dot(hidden, kernel, preferred_element_type=jnp.float32)
        .astype(jnp.float32)
    )
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if mask is None:
        return losses.sparse_softmax_cross_entropy(labels, logits)
    return losses.masked_sparse_softmax_cross_entropy(labels, logits, mask)


def _problem(rng, n=37, d=16, v=101, dtype=np.float32):
    h = rng.normal(size=(n, d)).astype(dtype)
    w = (rng.normal(size=(d, v)) * 0.3).astype(dtype)
    b = (rng.normal(size=(v,)) * 0.1).astype(np.float32)
    y = rng.integers(0, v, n).astype(np.int32)
    return h, y, w, b


@pytest.mark.parametrize("chunk", [8, 16, 37, 64])
def test_matches_unfused_f32(rng, chunk):
    h, y, w, b = _problem(rng)
    fused = chunked_softmax_cross_entropy(h, y, w, b, chunk=chunk)
    ref = _oracle(h, y, w, b)
    np.testing.assert_allclose(float(fused), float(ref), rtol=1e-6)


def test_gradients_match_unfused_f32(rng):
    h, y, w, b = _problem(rng)

    gf = jax.grad(
        lambda h, w, b: chunked_softmax_cross_entropy(h, y, w, b, chunk=16),
        argnums=(0, 1, 2),
    )(h, w, b)
    gr = jax.grad(
        lambda h, w, b: _oracle(h, y, w, b), argnums=(0, 1, 2)
    )(h, w, b)
    for a, e in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=2e-5, atol=1e-7)


def test_masked_rows_are_excluded(rng):
    h, y, w, b = _problem(rng, n=24)
    mask = (rng.uniform(size=24) > 0.3).astype(np.float32)
    fused = chunked_softmax_cross_entropy(h, y, w, b, mask=mask, chunk=7)
    ref = _oracle(h, y, w, b, mask=mask)
    np.testing.assert_allclose(float(fused), float(ref), rtol=1e-6)
    # a masked row's hidden state must get zero gradient
    gh = jax.grad(
        lambda h: chunked_softmax_cross_entropy(h, y, w, b, mask=mask,
                                                chunk=7)
    )(jnp.asarray(h))
    dead = np.asarray(gh)[mask == 0.0]
    assert np.all(dead == 0.0)


def test_bias_free_head_matches_and_differentiates(rng):
    h, y, w, _ = _problem(rng, n=21)
    fused = chunked_softmax_cross_entropy(h, y, w, None, chunk=8)
    ref = _oracle(h, y, w, None)
    np.testing.assert_allclose(float(fused), float(ref), rtol=1e-6)
    gf = jax.grad(
        lambda h, w: chunked_softmax_cross_entropy(h, y, w, None, chunk=8),
        argnums=(0, 1),
    )(h, w)
    gr = jax.grad(lambda h, w: _oracle(h, y, w, None), argnums=(0, 1))(h, w)
    for a, e in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=2e-5, atol=1e-7)


def test_mask_gradient_matches_unfused(rng):
    """mask is a differentiable loss weight: d(loss)/d(mask) must equal the
    autodiff of the unfused masked mean (nll_i/D − T·[Σm>1]/D²)."""
    h, y, w, b = _problem(rng, n=19)
    mask = rng.uniform(0.2, 1.0, size=19).astype(np.float32)
    gm_f = jax.grad(
        lambda m: chunked_softmax_cross_entropy(h, y, w, b, mask=m, chunk=5)
    )(jnp.asarray(mask))
    gm_r = jax.grad(lambda m: _oracle(h, y, w, b, mask=m))(jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(gm_f), np.asarray(gm_r),
                               rtol=2e-5, atol=1e-7)


def test_bf16_params_close_to_f32_oracle(rng):
    h, y, w, b = _problem(rng, n=32, d=32, v=64)
    h16, w16 = h.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    fused = chunked_softmax_cross_entropy(h16, y, w16, b, chunk=16)
    ref = _oracle(jnp.asarray(h), y, jnp.asarray(w), b)
    np.testing.assert_allclose(float(fused), float(ref), rtol=3e-2)
    gh = jax.grad(
        lambda x: chunked_softmax_cross_entropy(x, y, w16, b, chunk=16)
    )(h16)
    assert gh.dtype == jnp.bfloat16
    gr = jax.grad(lambda x: _oracle(x, y, jnp.asarray(w), b))(jnp.asarray(h))
    rel = np.abs(np.asarray(gh, np.float32) - np.asarray(gr))
    assert float(rel.max()) <= 5e-2 * float(np.abs(np.asarray(gr)).max()) + 1e-4


def test_shape_validation(rng):
    h, y, w, b = _problem(rng, n=8, d=4, v=11)
    with pytest.raises(ValueError, match="rows, dim"):
        chunked_softmax_cross_entropy(h[None], y, w, b)
    with pytest.raises(ValueError, match="chunk"):
        chunked_softmax_cross_entropy(h, y, w, b, chunk=0)


# -- model/trainer integration ------------------------------------------------


def _lm_pair(**kw):
    from distkeras_tpu.models.lm import transformer_lm

    cfg = dict(vocab=97, maxlen=16, dim=32, heads=4, depth=1,
               dtype=jnp.float32)
    cfg.update(kw)
    plain = transformer_lm(**cfg)
    fused = transformer_lm(fused_ce=True, ce_chunk=8, **cfg)
    return plain, fused


def test_lm_fused_loss_step_matches_plain(rng):
    from distkeras_tpu.trainers import _make_loss_step
    from distkeras_tpu.ops.losses import get_loss

    plain, fused = _lm_pair()
    assert fused.fused_losses and "sparse_softmax_cross_entropy" in \
        fused.fused_losses
    params, nt = plain.init_np(0)
    toks = rng.integers(0, 97, size=(4, 17)).astype(np.int32)
    batch = (toks[:, :-1], toks[:, 1:])
    loss_name = "sparse_softmax_cross_entropy"
    step_p = _make_loss_step(plain, get_loss(loss_name), 1,
                             loss_name=loss_name)
    step_f = _make_loss_step(fused, get_loss(loss_name), 1,
                             loss_name=loss_name)
    (lp, _), gp = jax.value_and_grad(step_p, has_aux=True)(params, nt, batch)
    (lf, _), gf = jax.value_and_grad(step_f, has_aux=True)(params, nt, batch)
    np.testing.assert_allclose(float(lf), float(lp), rtol=1e-5)
    flat_p = jax.tree.leaves(gp)
    flat_f = jax.tree.leaves(gf)
    for a, e in zip(flat_f, flat_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=5e-4, atol=1e-6)


def test_lm_trains_with_fused_ce(rng):
    from distkeras_tpu.models.lm import next_token_dataset, transformer_lm
    from distkeras_tpu.trainers import ADAG

    period = 8
    spec = transformer_lm(vocab=period, maxlen=16, dim=32, heads=4, depth=1,
                          dtype=jnp.float32, fused_ce=True, ce_chunk=64)
    # the deterministic "count up mod period" language is quickly learnable
    rows = np.stack([
        (np.arange(13) + s) % period for s in rng.integers(0, period, 256)
    ]).astype(np.int32)
    ds = next_token_dataset(rows)
    tr = ADAG(spec, loss="sparse_softmax_cross_entropy",
              worker_optimizer="adam", learning_rate=5e-3, batch_size=32,
              communication_window=2, num_epoch=6, num_workers=2, seed=0)
    tr.train(ds, shuffle=True)
    hist = [float(l) for l in tr.get_history().losses()]
    assert np.isfinite(hist).all()
    assert np.mean(hist[-2:]) < 0.5 * np.mean(hist[:2])


def test_validator_scores_through_fused_loss(rng):
    """validation_data on a fused_ce model must not materialize full logits:
    the _Validator routes through the fused fn and reports the same val_loss
    as the unfused path (accuracy is undefined for per-token labels on both
    paths)."""
    from distkeras_tpu.models.lm import next_token_dataset
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.trainers import _Validator

    plain, fused = _lm_pair()
    name = "sparse_softmax_cross_entropy"
    params, nt = plain.init_np(0)
    rows = rng.integers(0, 97, size=(11, 17)).astype(np.int32)
    ds = next_token_dataset(rows)
    v_plain = _Validator(plain, get_loss(name), ds, ["features"], "label", 4)
    v_fused = _Validator(fused, get_loss(name), ds, ["features"], "label", 4,
                         fused_loss=fused.fused_losses[name])
    r_plain = v_plain(params, nt)
    r_fused = v_fused(params, nt)
    np.testing.assert_allclose(r_fused["val_loss"], r_plain["val_loss"],
                               rtol=1e-5)
    assert "val_accuracy" not in r_fused and "val_accuracy" not in r_plain


def test_mesh_trainer_strategy_warns_fused_loss_unused():
    """Strategy engines rebuild the forward and cannot consume the fused
    loss; MeshTrainer must say so instead of silently training unfused."""
    import pytest as _pytest

    from distkeras_tpu.trainers import MeshTrainer

    _, fused = _lm_pair()
    t = MeshTrainer(fused, loss="sparse_softmax_cross_entropy",
                    mesh_shape={"pp": 8}, strategy="pipeline", batch_size=8)
    with _pytest.warns(UserWarning, match="unfused"):
        try:
            t._build_engine()
        except Exception:
            pass  # the LM isn't pipeline-compatible; the warning is the test


def test_fused_ce_through_mesh_trainer_fsdp(rng):
    """The fused loss under real parameter sharding: MeshTrainer's spmd
    strategy with fsdp consumes ModelSpec.fused_losses (loss falls; the
    fused fn reads the SHARDED lm_head params inside the global jit)."""
    from distkeras_tpu.models.lm import next_token_dataset, transformer_lm
    from distkeras_tpu.trainers import MeshTrainer

    period = 8
    spec = transformer_lm(vocab=period, maxlen=16, dim=32, heads=4, depth=1,
                          dtype=jnp.float32, fused_ce=True, ce_chunk=64)
    rows = np.stack([
        (np.arange(13) + s) % period for s in rng.integers(0, period, 256)
    ]).astype(np.int32)
    ds = next_token_dataset(rows)
    t = MeshTrainer(spec, loss="sparse_softmax_cross_entropy",
                    worker_optimizer="adam", learning_rate=5e-3,
                    mesh_shape={"dp": 8}, parameter_sharding="fsdp",
                    batch_size=32, num_epoch=6)
    t.train(ds, shuffle=True)
    losses = [r["loss"] for r in t.history.records if "loss" in r]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-2:]) < 0.5 * np.mean(losses[:2])


@pytest.mark.slow  # remat+fused-ce composition; classifier remat equality pins stay fast
def test_lm_remat_gradient_and_decode_equality(rng):
    """transformer_lm(remat=True): same params tree, same gradients, same
    decode — only the backward's memory schedule changes; composes with
    fused_ce."""
    from distkeras_tpu.models import generate, transformer_lm
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.trainers import _make_loss_step

    cfg = dict(vocab=64, maxlen=32, dim=32, heads=4, depth=2,
               dtype=jnp.float32)
    plain = transformer_lm(**cfg)
    rem = transformer_lm(**cfg, remat=True)
    params, nt = plain.init_np(0)
    p2, _ = rem.init_np(0)
    assert jax.tree.structure(params) == jax.tree.structure(p2)
    toks = rng.integers(0, 64, size=(2, 17)).astype(np.int32)
    name = "sparse_softmax_cross_entropy"
    batch = (toks[:, :-1], toks[:, 1:])
    sp = _make_loss_step(plain, get_loss(name), 1, loss_name=name)
    sr = _make_loss_step(rem, get_loss(name), 1, loss_name=name)
    (lp, _), gp = jax.value_and_grad(sp, has_aux=True)(params, {}, batch)
    (lr, _), gr = jax.value_and_grad(sr, has_aux=True)(params, {}, batch)
    np.testing.assert_allclose(float(lr), float(lp), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(gr), jax.tree.leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    out_p = generate(plain, params, toks[:, :8], max_new_tokens=4)
    out_r = generate(rem, params, toks[:, :8], max_new_tokens=4)
    np.testing.assert_array_equal(out_p, out_r)

    fr = transformer_lm(**cfg, remat=True, fused_ce=True, ce_chunk=8)
    sf = _make_loss_step(fr, get_loss(name), 1, loss_name=name)
    (lf, _), gf = jax.value_and_grad(sf, has_aux=True)(params, {}, batch)
    np.testing.assert_allclose(float(lf), float(lp), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-6)
