"""Commit compression for the async PS path (parallel.compression).

Codec-level contracts (error bounds, wire-size reduction, restricted-pickle
safety), the error-feedback telescoping identity, and end-to-end: hogwild
trainers still learn with int8 and top-k commits over both the in-process
and the real TCP transport.
"""

import pickle

import numpy as np
import pytest

from distkeras_tpu.parallel.compression import (
    Codec,
    Int8Codec,
    TopKCodec,
    is_encoded,
    maybe_decode,
    register_codec,
    resolve_codec,
)
from tests.test_trainers import blobs_dataset, final_loss, model_spec


def _tree(rng, scale=1.0):
    return {
        "dense": {"kernel": (scale * rng.normal(size=(64, 32))).astype(np.float32),
                  "bias": (scale * rng.normal(size=32)).astype(np.float32)},
        "head": {"kernel": (scale * rng.normal(size=(32, 4))).astype(np.float32),
                 "bias": (scale * rng.normal(size=4)).astype(np.float32)},
    }


def test_int8_roundtrip_error_bound(rng):
    tree = _tree(rng)
    codec = Int8Codec()
    blob = codec.encode(tree)
    assert is_encoded(blob)
    out = codec.decode(blob)
    for k in ("dense", "head"):
        w = tree[k]["kernel"]
        step = np.max(np.abs(w)) / 127.0
        assert np.max(np.abs(out[k]["kernel"] - w)) <= 0.5 * step + 1e-7


def test_topk_keeps_exactly_the_largest(rng):
    codec = TopKCodec(frac=0.1)
    arr = rng.normal(size=(20, 10)).astype(np.float32)
    out = codec.decode(codec.encode({"w": arr}))["w"]
    k = 20  # ceil(0.1 * 200)
    nz = np.flatnonzero(out)
    assert len(nz) == k
    # the kept entries are exact and are the k largest magnitudes
    flat = arr.reshape(-1)
    top = np.argsort(np.abs(flat))[-k:]
    assert set(nz) == set(top)
    np.testing.assert_array_equal(out.reshape(-1)[nz], flat[nz])


def test_wire_bytes_shrink(rng):
    tree = _tree(rng)
    dense_bytes = len(pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL))
    int8_bytes = len(pickle.dumps(Int8Codec().encode(tree),
                                  protocol=pickle.HIGHEST_PROTOCOL))
    topk_bytes = len(pickle.dumps(TopKCodec(0.05).encode(tree),
                                  protocol=pickle.HIGHEST_PROTOCOL))
    assert int8_bytes < 0.35 * dense_bytes, (int8_bytes, dense_bytes)
    assert topk_bytes < 0.25 * dense_bytes, (topk_bytes, dense_bytes)


def test_blob_survives_the_restricted_wire(rng):
    """Encoded commits are plain containers + ndarrays — the restricted
    unpickler (networking.py) must pass them untouched."""
    import socket

    from distkeras_tpu import networking

    blob = TopKCodec(0.1).encode(_tree(rng))
    a, b = socket.socketpair()
    networking.send_data(a, {"action": "commit", "payload": blob})
    got = networking.recv_data(b)["payload"]
    a.close(); b.close()
    out, want = maybe_decode(got), maybe_decode(blob)
    for k in ("dense", "head"):
        np.testing.assert_array_equal(out[k]["kernel"], want[k]["kernel"])


def test_tuple_structured_trees_roundtrip(rng):
    """Container types must survive encode→decode exactly: the worker's
    error-feedback tree.map and the PS fold both require identical
    treedefs."""
    import jax

    tree = {"stack": (rng.normal(size=(8, 8)).astype(np.float32),
                      rng.normal(size=(8, 8)).astype(np.float32)),
            "lst": [rng.normal(size=24).astype(np.float32)]}
    for codec in (Int8Codec(), TopKCodec(0.5)):
        out = codec.decode(codec.encode(tree))
        assert (jax.tree.structure(out) == jax.tree.structure(tree)), codec.name


def test_maybe_decode_passthrough_and_unknown(rng):
    raw = _tree(rng)
    assert maybe_decode(raw) is raw          # dense commits untouched
    with pytest.raises(ValueError, match="unknown codec"):
        maybe_decode({"__dk_codec__": "nope", "tree": {}})


def test_resolve_codec():
    assert resolve_codec(None) is None
    assert isinstance(resolve_codec("int8"), Int8Codec)
    assert isinstance(resolve_codec("topk"), TopKCodec)
    c = TopKCodec(0.01)
    assert resolve_codec(c) is c
    with pytest.raises(ValueError, match="unknown compression"):
        resolve_codec("gzip")


def test_bf16_leaves_compress_and_keep_dtype(rng):
    """bf16 commit trees (bf16-param models) must actually compress —
    a silent dense passthrough would fake the wire savings — and decode
    back to bf16 so the PS fold and feedback math keep their dtypes."""
    import jax.numpy as jnp

    arr = np.asarray(jnp.asarray(rng.normal(size=(32, 32)), jnp.bfloat16))
    blob = Int8Codec().encode({"w": arr})
    leaf = blob["tree"]["w"]
    assert "__dk_leaf__" in leaf and leaf["q"].dtype == np.int8
    out = Int8Codec().decode(blob)["w"]
    assert out.dtype == arr.dtype
    step = float(np.max(np.abs(arr.astype(np.float32)))) / 127.0
    err = np.abs(out.astype(np.float32) - arr.astype(np.float32))
    # half a quantization step + bf16 representation granularity
    assert float(np.max(err)) <= 0.5 * step + 0.01


def test_custom_codec_registers_and_decodes_at_the_ps(rng):
    """The documented 'or a Codec instance' API end-to-end: a user codec
    resolves, auto-registers by name, and the PS-side maybe_decode finds
    it; a name collision with a different class is rejected loudly."""
    class HalfCodec(Codec):
        name = "half-test"

        def encode_leaf(self, arr):
            return {"h": arr.astype(np.float16)}

        def decode_leaf(self, blob):
            return blob["h"].astype(np.float32)

    c = resolve_codec(HalfCodec())
    tree = {"w": rng.normal(size=(8, 8)).astype(np.float32)}
    out = maybe_decode(c.encode(tree))  # PS-side dispatch by name
    np.testing.assert_allclose(out["w"], tree["w"], atol=1e-2)

    class Impostor(Codec):
        name = "half-test"

    with pytest.raises(ValueError, match="already registered"):
        resolve_codec(Impostor())
    with pytest.raises(TypeError, match="Codec subclass"):
        register_codec(object)


def test_error_feedback_telescopes(rng):
    """Transmitted stream + final residual == true delta stream, exactly."""
    from distkeras_tpu.workers import AsyncWorker

    w = AsyncWorker.__new__(AsyncWorker)  # codec plumbing only
    w.codec = TopKCodec(0.05)
    w._resid = None
    deltas = [_tree(np.random.default_rng(i)) for i in range(5)]
    sent_total = None
    for d in deltas:
        _, sent = w._compress(d)
        sent_total = (sent if sent_total is None else
                      {k: {kk: sent_total[k][kk] + sent[k][kk]
                           for kk in sent[k]} for k in sent})
    for k in ("dense", "head"):
        for kk in ("kernel", "bias"):
            true = sum(d[k][kk] for d in deltas)
            np.testing.assert_allclose(
                sent_total[k][kk] + w._resid[k][kk], true,
                rtol=1e-5, atol=1e-5,
            )


@pytest.mark.parametrize("compression", ["int8", "topk"])
def test_downpour_learns_with_compressed_commits(compression):
    from distkeras_tpu import DOWNPOUR

    ds = blobs_dataset(n=2048)
    t = DOWNPOUR(model_spec(), loss="sparse_softmax_cross_entropy",
                 worker_optimizer="sgd", learning_rate=0.02, num_workers=4,
                 batch_size=32, communication_window=2, num_epoch=3,
                 backend="ps", compression=compression)
    t.train(ds, shuffle=True)
    assert final_loss(t) < 0.6, f"{compression}: {final_loss(t)}"


def test_aeasgd_learns_with_compressed_elastic_commits():
    from distkeras_tpu import AEASGD

    ds = blobs_dataset(n=2048)
    t = AEASGD(model_spec(), loss="sparse_softmax_cross_entropy",
               worker_optimizer="sgd", learning_rate=0.05, rho=0.5,
               num_workers=4, batch_size=32, communication_window=4,
               num_epoch=3, backend="ps", compression="int8")
    t.train(ds, shuffle=True)
    assert final_loss(t) < 0.6, final_loss(t)


def test_compressed_commits_over_real_tcp():
    """Server-side decode across the actual socket transport."""
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=1024)
    t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="sgd", learning_rate=0.1, num_workers=2,
             batch_size=32, communication_window=2, num_epoch=2,
             backend="ps", ps_transport="socket", compression="topk")
    t.train(ds, shuffle=True)
    assert final_loss(t) < 0.6


def test_compression_rejected_off_the_ps_backend():
    from distkeras_tpu import ADAG, DOWNPOUR

    with pytest.raises(ValueError, match="backend='ps'"):
        ADAG(model_spec(), num_workers=2, compression="int8")
    # the native C++ wire carries int8 only — other codecs need the
    # pickle wire (int8 itself is accepted; see test_native_ps.py)
    with pytest.raises(ValueError, match="int8"):
        DOWNPOUR(model_spec(), num_workers=2, backend="ps",
                 ps_transport="native", compression="topk")
    with pytest.raises(ValueError, match="unknown compression"):
        DOWNPOUR(model_spec(), num_workers=2, backend="ps",
                 compression="gzip")
