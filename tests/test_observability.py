"""ISSUE 11: the flight recorder — tracing, metrics surface, health.

Pins, per the acceptance criteria:

- span nesting/ordering and the Chrome-trace JSON shape (Perfetto
  loadable: ``ph: "X"`` complete events with µs timestamps + thread
  metadata);
- the off path is allocation-free on the hot-path entry points
  (``span``/``record``/``instant``/``set_corr``);
- cross-process correlation-id stitching: the worker-side exchange span
  and the PS-side fold/WAL-append spans share one id, over the socket
  frame corr AND the native wire's (wid, seqno);
- the Prometheus text exposition format of the unified metrics surface,
  and the ``metrics``/``stats`` wire actions serving it live;
- the stats settling barrier: end-of-run counter reads are EXACT (the
  PR 10 delivered-traffic ≤1-per-worker tolerance is retired);
- the acceptance run: seeded kill + drops, 2 workers, WAL on → ONE
  trace file in which the same fused EXCHANGE's worker-side span and
  PS-side fold/WAL-append spans share a correlation id.
"""

import gc
import json
import os
import sys
import threading
import time
import warnings

import numpy as np
import pytest

from distkeras_tpu.observability import trace
from distkeras_tpu.observability.metrics import (
    MetricsRegistry,
    health_snapshot,
    ps_metrics,
    serving_metrics,
)
from distkeras_tpu.parallel.merge_rules import DownpourMerge
from distkeras_tpu.parameter_servers import (
    ParameterServer,
    ParameterServerClient,
    SocketParameterServer,
    build_ps_stats,
)


@pytest.fixture(autouse=True)
def _trace_off():
    """Every test starts and ends with tracing disabled — a leaked
    global tracer would silently contaminate later tests' off-path
    assertions."""
    trace.disable()
    yield
    trace.disable()


# -- the span API ------------------------------------------------------------


def test_span_nesting_and_ordering():
    trace.enable()
    with trace.span("outer"):
        with trace.span("inner.a"):
            pass
        with trace.span("inner.b"):
            pass
    evs = trace.events()
    by = {e["name"]: e for e in evs}
    assert set(by) == {"outer", "inner.a", "inner.b"}
    out, a, b = by["outer"], by["inner.a"], by["inner.b"]
    # containment: children start after the parent and end before it
    for child in (a, b):
        assert out["t0_ns"] <= child["t0_ns"]
        assert child["t0_ns"] + child["dur_ns"] \
            <= out["t0_ns"] + out["dur_ns"]
    # ordering: a before b, and events() is sorted by start time
    assert a["t0_ns"] + a["dur_ns"] <= b["t0_ns"]
    assert [e["t0_ns"] for e in evs] == sorted(e["t0_ns"] for e in evs)


def test_off_mode_is_allocation_free_on_the_hot_path():
    """The zero-cost-when-off contract: with tracing disabled, the hot
    call sites (span enter/exit, retroactive record, corr set, instant)
    allocate NOTHING — measured with the allocator's live-block count,
    GC off, after a warm-up pass."""
    assert not trace.enabled()

    def hot(n):
        s = trace.span
        for _ in range(n):
            with s("worker.fetch"):
                pass
            trace.record("worker.commit", 1, 2)
            trace.set_corr("w0:x1")
            trace.instant("ps.join")

    hot(16)  # warm-up: caches, code objects, int freelists
    gc.collect()
    gc.disable()
    try:
        before = sys.getallocatedblocks()
        hot(10_000)
        after = sys.getallocatedblocks()
    finally:
        gc.enable()
    # a single allocation per call would cost >= 40k live or transient
    # blocks here; the interpreter itself wanders by a handful (caches,
    # freelist growth), so the bound is "orders of magnitude below one
    # per call", not literal zero
    assert after - before < 100, \
        f"off-path allocated {after - before} blocks over 40k calls"


def test_corr_inheritance_at_close_and_explicit_override():
    trace.enable()
    trace.set_corr("w1:x1")
    with trace.span("a"):
        # corr resolves when the span CLOSES — a wire call that assigns
        # the seqno mid-span re-stamps it
        trace.set_corr("w1:s9")
    trace.record("b", 10, 20)                 # inherits current corr
    trace.record("c", 10, 20, corr="explicit")
    by = {e["name"]: e["corr"] for e in trace.events()}
    assert by == {"a": "w1:s9", "b": "w1:s9", "c": "explicit"}


def test_ring_overflow_drops_oldest():
    trace.enable(ring_size=16)
    for i in range(20):
        trace.record(f"s{i}", i, i + 1)
    evs = trace.events()
    assert len(evs) == 16
    assert [e["name"] for e in evs] == [f"s{i}" for i in range(4, 20)]
    assert trace._tracer.dropped() == 4


def test_deterministic_sampling_keeps_exact_fraction():
    trace.enable(sample=0.5)
    for i in range(100):
        trace.record(f"s{i}", i, i + 1)
    assert len(trace.events()) == 50


def test_save_writes_perfetto_loadable_chrome_trace(tmp_path):
    trace.enable()
    trace.set_corr("w0:s1")
    with trace.span("worker.commit", args={"k": 1}):
        pass
    path = trace.save(str(tmp_path / "t" / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in meta)
    assert any(e["name"] == "thread_name" for e in meta)
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 1
    x = xs[0]
    assert x["name"] == "worker.commit"
    assert x["args"]["corr"] == "w0:s1" and x["args"]["k"] == 1
    assert isinstance(x["ts"], float) and x["dur"] >= 0
    assert doc["otherData"]["dropped_events"] == 0


def test_save_without_enable_raises():
    with pytest.raises(RuntimeError):
        trace.save("/tmp/never-written.json")


def test_enable_is_idempotent_and_keeps_the_outer_recorder():
    t1 = trace.enable()
    trace.record("kept", 1, 2)
    t2 = trace.enable(ring_size=32)  # nested enable must NOT reset
    assert t1 is t2
    assert [e["name"] for e in trace.events()] == ["kept"]


# -- the metrics surface -----------------------------------------------------


def test_prometheus_exposition_format():
    s = build_ps_stats(10, 2, 8, 100, 200, 20, 5, 7, 2.0,
                       dup_commits=1, fused_exchanges=3, num_updates=8)
    s["exchange_phases"] = {
        "fetch": {"count": 4, "total_ms": 2.0, "max_ms": 1.0,
                  "hist_ms_le": [0.25, 0.5, "inf"], "hist": [1, 2, 1]},
    }
    text = ps_metrics(s).to_prometheus()
    lines = text.splitlines()
    # typed headers + exact sample values
    assert "# TYPE dk_ps_pulls_total counter" in lines
    assert "dk_ps_pulls_total 10" in lines
    assert "# TYPE dk_ps_num_updates gauge" in lines
    assert "dk_ps_num_updates 8" in lines
    assert "dk_ps_fused_exchanges_total 3" in lines
    # histogram expansion: cumulative buckets + +Inf + sum/count
    assert "# TYPE dk_worker_exchange_phase_ms histogram" in lines
    assert 'dk_worker_exchange_phase_ms_bucket{phase="fetch",le="0.25"} 1' \
        in lines
    assert 'dk_worker_exchange_phase_ms_bucket{phase="fetch",le="0.5"} 3' \
        in lines
    assert 'dk_worker_exchange_phase_ms_bucket{phase="fetch",le="+Inf"} 4' \
        in lines
    assert 'dk_worker_exchange_phase_ms_count{phase="fetch"} 4' in lines
    # every non-comment line parses as `name[{labels}] value`
    for ln in lines:
        if ln.startswith("#") or not ln:
            continue
        name, val = ln.rsplit(" ", 1)
        float(val)
        assert name[0].isalpha()


def test_prometheus_label_escaping():
    reg = MetricsRegistry()
    reg.gauge("dk_test", 1, labels={"p": 'a"b\\c\nd'})
    assert r'dk_test{p="a\"b\\c\nd"} 1' in reg.to_prometheus()


def test_registry_rejects_kind_conflicts():
    reg = MetricsRegistry()
    reg.counter("dk_x_total", 1)
    with pytest.raises(ValueError):
        reg.gauge("dk_x_total", 2)


def test_ps_metrics_fans_out_per_shard_labels():
    shard0 = build_ps_stats(4, 0, 4, 1, 1, 8, 0, 0, 1.0)
    shard0["shard_id"] = 0
    shard1 = build_ps_stats(6, 0, 6, 1, 1, 12, 0, 0, 1.0)
    shard1["shard_id"] = 1
    agg = build_ps_stats(10, 0, 10, 2, 2, 20, 0, 0, 1.0)
    agg["per_shard"] = [shard0, shard1]
    text = ps_metrics(agg).to_prometheus()
    assert "dk_ps_pulls_total 10" in text            # the aggregate
    assert 'dk_ps_pulls_total{shard="0"} 4' in text  # labeled series
    assert 'dk_ps_pulls_total{shard="1"} 6' in text


def test_serving_metrics_normalization():
    stats = {"submitted": 5, "completed": 4, "queued": 1, "active": 2,
             "blocks_in_use": 7, "tokens_generated": 40}
    text = serving_metrics(stats).to_prometheus()
    assert "dk_serve_submitted_total 5" in text
    assert "dk_serve_queue_depth 1" in text
    assert "dk_serve_blocks_in_use 7" in text


def test_health_snapshot_one_document(tmp_path):
    wal_dir = str(tmp_path / "wal")
    ps = ParameterServer({"w": np.zeros(32, np.float32)}, DownpourMerge(),
                         2, wal_dir=wal_dir)
    for k in range(6):
        ps.pull(k % 2)
        ps.commit(k % 2, {"w": np.full(32, 0.1, np.float32)}, seq=k + 1)
    stats = ps.stats()
    ps.stop()
    doc = health_snapshot(wal_root=wal_dir, ps_stats=stats)
    json.dumps(doc)  # JSON-clean end to end
    assert doc["ok"]
    assert doc["wal"]["record_totals"]["commit"] == 6
    assert doc["membership"]["num_updates"] == 6
    assert "dk_ps_commits_total" in doc["metrics"]
    assert doc["metrics"]["dk_ps_commits_total"]["samples"][0]["value"] \
        == 6


def test_health_cli(tmp_path, capsys):
    from distkeras_tpu.observability.__main__ import main as obs_main

    wal_dir = str(tmp_path / "wal")
    ps = ParameterServer({"w": np.zeros(16, np.float32)}, DownpourMerge(),
                         1, wal_dir=wal_dir)
    ps.pull(0)
    ps.commit(0, {"w": np.ones(16, np.float32)}, seq=1)
    ps.stop()
    rc = obs_main(["health", "--wal-dir", wal_dir])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["ok"]
    assert doc["wal"]["record_totals"]["commit"] == 1


# -- live wire actions + the settling barrier --------------------------------


def _socket_ps(tmp_path=None, num_workers=1, **kw):
    ps = SocketParameterServer(
        {"w": np.zeros(8, np.float32)}, DownpourMerge(), num_workers,
        **kw,
    )
    ps.initialize()
    ps.start()
    return ps


def test_stats_settling_barrier_makes_end_of_run_reads_exact():
    """The ISSUE 11 counter-lag fix, unit level: the moment a client has
    RECEIVED a pull/exchange reply, a stats() read must count it — the
    server settles in-flight reply windows before reading."""
    ps = _socket_ps()
    try:
        c = ParameterServerClient("127.0.0.1", ps.port, 0)
        for _ in range(5):
            c.pull()
        for k in range(3):
            c.exchange(0, {"w": np.ones(8, np.float32)}, seq=k + 1)
        s = ps.stats()  # immediately — no sleep, no tolerance
        assert s["pulls"] == 8          # 5 standalone + 3 fused halves
        assert s["commits"] == 3
        assert s["fused_exchanges"] == 3
        assert s["exchange_rtts"] == 8
        c.close()
    finally:
        ps.stop()


def test_metrics_and_stats_wire_actions():
    from distkeras_tpu import networking

    ps = _socket_ps()
    try:
        c = ParameterServerClient("127.0.0.1", ps.port, 0)
        c.pull()
        sock = networking.connect("127.0.0.1", ps.port)
        networking.send_data(sock, {"action": "stats"})
        reply = networking.recv_data(sock)
        assert reply["ok"] and reply["stats"]["pulls"] == 1
        networking.send_data(sock, {"action": "metrics"})
        reply = networking.recv_data(sock)
        assert reply["ok"]
        assert "dk_ps_pulls_total 1" in reply["prom"]
        assert reply["metrics"]["dk_ps_pulls_total"]["kind"] == "counter"
        networking.send_data(sock, {"action": "bye"})
        sock.close()
        c.close()
    finally:
        ps.stop()


def test_observability_cli_dump_against_live_ps(capsys):
    from distkeras_tpu.observability.__main__ import main as obs_main

    ps = _socket_ps()
    try:
        rc = obs_main(["dump", "--port", str(ps.port), "--prom"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "# TYPE dk_ps_commits_total counter" in out
        rc = obs_main(["tail", "--port", str(ps.port), "--count", "2",
                       "--interval", "0.05"])
        out = capsys.readouterr().out
        assert rc == 0 and len(out.strip().splitlines()) == 2
    finally:
        ps.stop()


# -- cross-process correlation stitching -------------------------------------


def test_socket_correlation_stitching_with_wal(tmp_path):
    """One fused EXCHANGE over the socket wire: the worker-side span,
    the PS handler's fold span, and the WAL-append span all close under
    the resilient client's ``w<id>:s<seq>`` correlation id (the frame
    carries it; the handler thread adopts it)."""
    from distkeras_tpu.resilience.retry import ResilientPSClient

    trace.enable()
    ps = _socket_ps(wal_dir=str(tmp_path / "wal"))
    try:
        c = ResilientPSClient(
            lambda: ParameterServerClient("127.0.0.1", ps.port, 0), 0,
        )
        c.pull(0)
        with trace.span("worker.exchange"):
            c.exchange(0, {"w": np.ones(8, np.float32)})
        corr = trace.current_corr()
        assert corr is not None and corr.startswith("w0:s")

        def names_with(corr_):
            return {e["name"] for e in trace.events()
                    if e["corr"] == corr_}

        # The handler's ``ps.exchange`` span wraps the reply send, so it
        # closes AFTER the client's exchange() returns — give the server
        # thread a beat to land it before reading the event log.
        deadline = time.monotonic() + 5.0
        got = names_with(corr)
        while "ps.exchange" not in got and time.monotonic() < deadline:
            time.sleep(0.01)
            got = names_with(corr)
        assert "worker.exchange" in got
        assert "ps.fold" in got
        assert "ps.wal_append" in got
        assert "ps.exchange" in got  # the handler's serve span
        c.close()
    finally:
        ps.stop()


def test_native_correlation_stitching(tmp_path):
    """The same stitching over the native wire: the C++ span ring
    records (wid, seqno) per fold/WAL-wait section, and the scraper
    rebuilds the SAME ``w<id>:s<seq>`` id the resilient client stamped
    worker-side."""
    from distkeras_tpu.native import load_dkps

    if load_dkps() is None:
        pytest.skip("no C++ toolchain to build libdkps")
    from distkeras_tpu.native_ps import (
        NativePSClient,
        NativeSocketParameterServer,
    )
    from distkeras_tpu.resilience.retry import ResilientPSClient

    trace.enable()
    srv = NativeSocketParameterServer(
        {"w": np.zeros(32, np.float32)}, DownpourMerge(), 1,
        wal_dir=str(tmp_path / "wal"),
    )
    srv.initialize()
    srv.start()
    srv.set_trace(True)
    try:
        c = ResilientPSClient(
            lambda: NativePSClient("127.0.0.1", srv.port, 0, srv.spec),
            0,
        )
        c.pull(0)
        with trace.span("worker.exchange"):
            c.exchange(0, {"w": np.ones(32, np.float32)})
        corr = trace.current_corr()
        assert corr is not None and corr.startswith("w0:s")
        native = srv.scrape_trace_events()
        assert any(e["name"] == "ps.fold" and e["corr"] == corr
                   for e in native), native
        assert any(e["name"] == "ps.wal_wait" and e["corr"] == corr
                   for e in native), native
        assert any(e["name"] == "wal.fsync" for e in native), native
        # merged into ONE timeline next to the worker-side span
        trace.add_events(native)
        evs = trace.events()
        got = {e["name"] for e in evs if e["corr"] == corr}
        assert {"worker.exchange", "ps.fold", "ps.wal_wait"} <= got
        # a second scrape is empty: the ring drains on read
        assert srv.scrape_trace_events() == []
        c.close()
    finally:
        srv.stop()


# -- trainer integration + the acceptance run --------------------------------


def test_trainer_knob_validation():
    import distkeras_tpu as dk

    from tests.test_trainers import model_spec

    with pytest.raises(ValueError, match="backend='ps'"):
        dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", num_workers=2, trace=True)
    with pytest.raises(ValueError, match="trace_sample"):
        dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", num_workers=2, backend="ps",
                trace=True, trace_sample=0.0)


def test_inprocess_trainer_trace_writes_timeline(tmp_path):
    """A plain in-process PS run with trace_dir=: the timeline file
    exists, loads, and carries the worker phase spans + PS fold spans —
    and the recorder is disabled again once the run returns."""
    import distkeras_tpu as dk

    from tests.test_trainers import blobs_dataset, model_spec

    ds = blobs_dataset(n=256)
    t = dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", learning_rate=0.05,
                num_workers=2, batch_size=16, communication_window=2,
                num_epoch=1, backend="ps",
                trace_dir=str(tmp_path / "traces"))
    t.train(ds, shuffle=False)
    assert not trace.enabled()  # the run owned and released the recorder
    assert t.trace_path_ is not None and os.path.exists(t.trace_path_)
    with open(t.trace_path_) as f:
        doc = json.load(f)
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"worker.fetch", "worker.compress", "worker.commit",
            "ps.fold"} <= names


def test_acceptance_chaos_trace_stitches_one_exchange(tmp_path):
    """THE acceptance criterion: a seeded kill + drops chaos run
    (2 workers, WAL on, socket transport) produces ONE Perfetto-loadable
    trace file in which the same fused EXCHANGE's worker-side span and
    the PS-side fold / WAL-append spans share a correlation id."""
    import distkeras_tpu as dk

    from distkeras_tpu.resilience.faults import FaultPlan
    from distkeras_tpu.resilience.retry import RetryPolicy
    from tests.test_trainers import blobs_dataset, model_spec

    ds = blobs_dataset(n=512)
    plan = FaultPlan(seed=13, drop_recv=0.02, delay=0.03, delay_s=0.002,
                     kill_ps_after_commits=6, max_faults=30)
    t = dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", learning_rate=0.05,
                num_workers=2, batch_size=16, communication_window=2,
                num_epoch=2, backend="ps", ps_transport="socket",
                ps_wal_dir=str(tmp_path / "wal"), ps_snapshot_every=5,
                ps_failover_timeout=0.4,
                retry_policy=RetryPolicy(max_attempts=100,
                                         base_delay=0.005,
                                         max_delay=0.2, deadline=120),
                heartbeat_interval=0.05, fault_plan=plan,
                trace_dir=str(tmp_path / "traces"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # failover warning expected
        with plan:
            t.train(ds, shuffle=True)
    assert plan.stats()["ps_kills"] == 1  # the kill really happened
    assert t.trace_path_ and os.path.exists(t.trace_path_)
    with open(t.trace_path_) as f:
        doc = json.load(f)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    by_corr: dict = {}
    for e in xs:
        corr = (e.get("args") or {}).get("corr")
        if corr:
            by_corr.setdefault(corr, set()).add(e["name"])
    stitched = [
        corr for corr, names in by_corr.items()
        if corr.startswith("w") and ":s" in corr
        and "worker.commit" in names and "ps.fold" in names
        and "ps.wal_append" in names
    ]
    assert stitched, (
        "no exchange stitched across worker + PS fold + WAL append: "
        f"{ {k: sorted(v) for k, v in list(by_corr.items())[:8]} }"
    )
    # the failover itself is on the timeline too
    assert any(e["name"] == "ps.failover" for e in xs)
    # and the run still holds the exactly-once oracle under tracing
    s = t.ps_stats_
    assert s["num_updates"] == t.resilience_stats_["logical_commits"]


def test_trace_disabled_run_records_nothing():
    """Tracing stays fully off by default: a traced-site workload leaves
    the module recorder empty and disabled."""
    ps = ParameterServer({"w": np.zeros(4, np.float32)}, DownpourMerge(),
                         1)
    ps.pull(0)
    ps.exchange(0, {"w": np.ones(4, np.float32)}, seq=1)
    assert not trace.enabled()
    assert trace.events() == []
    assert trace.current_corr() is None
