"""Resilience subsystem: faults, leases, retry/dedup, recovery, chaos.

The acceptance oracle threaded through this file: under injected drops,
delays, and worker kills, a PS run must (a) complete, (b) converge, and
(c) fold every logical commit EXACTLY once — ``ps.stats()['commits'] ==
sum of client seqnos`` — no matter how many retries replayed a commit
whose ACK died. Heartbeat eviction and retry counts must be visible in
``ps.stats()`` throughout.
"""

import threading
import time

import numpy as np
import pytest

from distkeras_tpu import networking
from distkeras_tpu.networking import ProtocolError
from distkeras_tpu.parallel.merge_rules import DownpourMerge, DynSGDMerge
from distkeras_tpu.parameter_servers import (
    ParameterServer,
    ParameterServerClient,
    SocketParameterServer,
)
from distkeras_tpu.resilience import (
    FaultInjectedError,
    FaultPlan,
    ResilientPSClient,
    RetryDeadlineExceeded,
    RetryPolicy,
    WorkerRegistry,
    is_retryable,
)
from tests.test_trainers import blobs_dataset, final_loss, model_spec


# ---------------------------------------------------------------------------
# networking: typed ProtocolError
# ---------------------------------------------------------------------------


def test_protocol_error_mid_frame_is_retryable_with_context():
    import socket as _socket
    import struct

    a, b = _socket.socketpair()
    # announce a 100-byte frame, deliver 10, die
    a.sendall(struct.pack(">Q", 100) + b"x" * 10)
    a.close()
    with pytest.raises(ProtocolError) as ei:
        networking.recv_data(b)
    assert ei.value.retryable is True
    assert ei.value.frame_size == 100
    b.close()


def test_protocol_error_oversized_frame_is_fatal():
    import socket as _socket
    import struct

    a, b = _socket.socketpair()
    a.sendall(struct.pack(">Q", networking.MAX_FRAME_BYTES + 1))
    with pytest.raises(ProtocolError) as ei:
        networking.recv_data(b)
    assert ei.value.retryable is False
    assert ei.value.frame_size == networking.MAX_FRAME_BYTES + 1
    # still a ConnectionError: pre-existing handlers keep catching it
    assert isinstance(ei.value, ConnectionError)
    a.close(); b.close()


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


def test_retry_policy_deterministic_backoff_with_jitter():
    p = RetryPolicy(base_delay=0.1, max_delay=1.0, jitter=0.5, seed=42)
    d1 = [p.delays().next_delay() for _ in range(1)]  # fresh seq each call
    s1 = p.delays()
    s2 = p.delays()
    a = [s1.next_delay() for _ in range(6)]
    b = [s2.next_delay() for _ in range(6)]
    assert a == b  # seeded: identical across sequences
    assert a[0] == d1[0]
    # exponential growth up to the cap, jitter only ever scales DOWN
    raw = [min(0.1 * 2 ** k, 1.0) for k in range(6)]
    for got, r in zip(a, raw):
        assert 0.5 * r <= got <= r


def test_retry_policy_triage_and_deadline():
    assert is_retryable(ConnectionResetError("peer died"))
    assert is_retryable(ProtocolError("torn", retryable=True))
    assert not is_retryable(ProtocolError("cap", retryable=False))
    assert not is_retryable(ValueError("a bug"))

    p = RetryPolicy(max_attempts=3, base_delay=0.001, deadline=10.0)
    calls = []

    def flaky():
        calls.append(1)
        raise ConnectionError("down")

    with pytest.raises(RetryDeadlineExceeded):
        p.run(flaky)
    assert len(calls) == 3  # max_attempts honored

    # non-retryable propagates immediately, untouched
    calls.clear()

    def buggy():
        calls.append(1)
        raise ValueError("shape mismatch")

    with pytest.raises(ValueError, match="shape mismatch"):
        p.run(buggy)
    assert len(calls) == 1

    # deadline: a slow clock exhausts the budget before max_attempts
    t = [0.0]

    def clock():
        return t[0]

    def sleep(s):
        t[0] += s

    slow = RetryPolicy(max_attempts=100, base_delay=1.0, max_delay=1.0,
                       deadline=2.5, jitter=0.0)
    with pytest.raises(RetryDeadlineExceeded, match="deadline"):
        slow.run(flaky, clock=clock, sleep=sleep)


# ---------------------------------------------------------------------------
# FaultPlan
# ---------------------------------------------------------------------------


def test_fault_plan_deterministic_and_uninstalls():
    def decisions(plan):
        out = []
        for _ in range(64):
            try:
                plan._wire("recv", None)
                out.append(False)
            except FaultInjectedError:
                out.append(True)
        return out

    a = decisions(FaultPlan(seed=7, drop_recv=0.3))
    b = decisions(FaultPlan(seed=7, drop_recv=0.3))
    assert a == b and any(a) and not all(a)
    assert decisions(FaultPlan(seed=8, drop_recv=0.3)) != a

    plan = FaultPlan(seed=0)
    with plan:
        assert networking._fault_hook == plan._wire
        with pytest.raises(RuntimeError, match="already installed"):
            FaultPlan(seed=1).install()
    assert networking._fault_hook is None


def test_fault_plan_partition_window_and_budget():
    plan = FaultPlan(seed=0, partition_after=3, partition_ops=2)
    hits = []
    for _ in range(8):
        try:
            plan._wire("send", None)
            hits.append(False)
        except FaultInjectedError:
            hits.append(True)
    assert hits == [False, False, False, True, True, False, False, False]

    capped = FaultPlan(seed=0, drop_send=1.0, max_faults=2)
    dropped = 0
    for _ in range(10):
        try:
            capped._wire("send", None)
        except FaultInjectedError:
            dropped += 1
    assert dropped == 2  # budget bounds chaos: runs always drain
    assert capped.stats()["drops"] == 2


def test_fault_plan_kill_fires_once():
    from distkeras_tpu.resilience import WorkerKilled

    plan = FaultPlan(kill_at={1: 3})
    plan.maybe_kill(1, 2)  # not yet
    plan.maybe_kill(0, 3)  # wrong worker
    with pytest.raises(WorkerKilled, match="worker 1 at window 3"):
        plan.maybe_kill(1, 3)
    plan.maybe_kill(1, 3)  # a restarted worker replays the window unharmed
    assert plan.stats()["kills"] == 1


# ---------------------------------------------------------------------------
# WorkerRegistry: leases, eviction, retry accounting
# ---------------------------------------------------------------------------


def test_registry_lease_lifecycle_with_fake_clock():
    t = [0.0]
    evicted: list[int] = []
    reg = WorkerRegistry(lease_timeout=10.0, clock=lambda: t[0],
                         on_evict=evicted.extend)
    assert reg.renew(0) is False          # first heartbeat registers
    assert reg.renew(0, retries=2) is True
    reg.renew(1)
    assert reg.active() == [0, 1]
    t[0] = 8.0
    reg.renew(1)                          # 1 stays fresh, 0 lapses at 10
    t[0] = 12.0
    assert reg.expire() == [0]
    assert evicted == [0]
    s = reg.stats()
    assert s["active_workers"] == 1
    assert s["evicted_workers"] == 1
    assert s["worker_retries"] == 2       # evicted worker's count retained
    assert reg.renew(0) is False          # re-admission after eviction
    # the re-admitted worker re-reports its CUMULATIVE count: no
    # double-count across the eviction cycle (max per id, not a sum)
    reg.renew(0, retries=3)
    assert reg.stats()["worker_retries"] == 3
    # clean deregister: no eviction counted
    reg.deregister(1)
    t[0] = 100.0
    reg.expire()
    assert reg.stats()["evicted_workers"] == 2  # only worker 0 (twice)


def test_ps_eviction_feeds_dynsgd_staleness():
    """An evicted worker's pull version is forgotten: its zombie commit is
    scaled as maximally stale (1/(num_updates+1)) instead of fresh."""
    center = {"w": np.zeros(1, np.float32)}
    ps = ParameterServer(center, DynSGDMerge(), 3, lease_timeout=0.05)
    ps.pull(0)
    ps.heartbeat(0)
    # two commits land from a live worker while 0 is silent
    for k in range(4):
        ps.pull(1)
        ps.commit(1, {"w": np.array([4.0], np.float32)})  # τ=0 → +4 each
    time.sleep(0.12)
    ps.stats()  # expiry pass evicts worker 0
    assert ps.stats()["evicted_workers"] == 1
    assert 0 not in ps._pull_versions
    # zombie commit: τ = num_updates (4) → scale 1/5, NOT the 1/1 its
    # stale pull-version record would have granted
    ps.commit(0, {"w": np.array([5.0], np.float32)})
    np.testing.assert_allclose(ps.get_model()["w"], 16.0 + 5.0 / 5.0)


# ---------------------------------------------------------------------------
# Commit seqno dedup: the exactly-once oracle
# ---------------------------------------------------------------------------


def test_seqno_dedup_inprocess():
    ps = ParameterServer({"w": np.zeros(2, np.float32)}, DownpourMerge(), 1)
    d = {"w": np.ones(2, np.float32)}
    assert ps.commit(0, d, seq=1) is True
    assert ps.commit(0, d, seq=1) is False   # replay refused
    assert ps.commit(0, d, seq=2) is True
    assert ps.commit(0, d) is True           # legacy seq-less commit folds
    assert ps.num_updates == 3
    s = ps.stats()
    assert s["commits"] == 3 and s["dup_commits"] == 1
    np.testing.assert_allclose(ps.get_model()["w"], 3.0)


def test_seqno_dedup_over_socket_wire():
    ps = SocketParameterServer({"w": np.zeros(2, np.float32)},
                               DownpourMerge(), 1)
    ps.initialize()
    ps.start()
    try:
        c = ParameterServerClient("127.0.0.1", ps.port, 0)
        d = {"w": np.ones(2, np.float32)}
        c.commit(0, d, seq=1)
        c.commit(0, d, seq=1)
        c.commit(0, d, seq=2)
        c.close()
        assert ps.num_updates == 2
        assert ps.stats()["dup_commits"] == 1
    finally:
        ps.stop()


def test_resilient_client_replays_lost_acks_exactly_once():
    """The canonical double-fold hazard, deterministically: the inner
    commit SUCCEEDS server-side, then the ack 'dies'. The resilient
    client retries with the same seq; the server must fold once."""
    ps = ParameterServer({"w": np.zeros(3, np.float32)}, DownpourMerge(), 1)
    lose_acks = [3]  # next N commit acks vanish after the server applied

    class LossyBound:
        def __init__(self):
            from distkeras_tpu.workers import _BoundPS

            self._inner = _BoundPS(ps, 0)

        def pull(self, worker_id=None):
            return self._inner.pull()

        def commit(self, worker_id, payload, seq=None):
            self._inner.commit(worker_id, payload, seq=seq)
            if lose_acks[0] > 0:
                lose_acks[0] -= 1
                raise FaultInjectedError("ack lost after apply")

        def heartbeat(self, retries=0):
            return ps.heartbeat(0, retries=retries)

        def close(self):
            pass

    c = ResilientPSClient(
        LossyBound, 0,
        policy=RetryPolicy(base_delay=0.001, max_delay=0.01, deadline=10),
    )
    d = {"w": np.ones(3, np.float32)}
    for _ in range(5):
        c.commit(0, d)
    c.heartbeat()
    s = ps.stats()
    assert c.seq == 5                      # five logical commits
    assert ps.num_updates == 5             # five folds — not eight
    assert s["commits"] == 5
    assert s["dup_commits"] == 3           # the three replays, refused
    assert s["worker_retries"] == c.retries == 3
    np.testing.assert_allclose(ps.get_model()["w"], 5.0)


def test_fresh_client_seqnos_survive_a_long_lived_ps():
    """A NEW run against a long-lived external PS restarts its commit
    counter; epoch-based wire seqnos keep its commits from being swallowed
    by the previous run's dedup fence — even when the old run crashed
    without deregistering."""
    from distkeras_tpu.workers import _BoundPS

    ps = ParameterServer({"w": np.zeros(1, np.float32)}, DownpourMerge(), 1)
    d = {"w": np.ones(1, np.float32)}
    c1 = ResilientPSClient(lambda: _BoundPS(ps, 0), 0)
    for _ in range(3):
        c1.commit(0, d)
    # run 1 "crashes": no close(), no deregister — the fence stays up
    c2 = ResilientPSClient(lambda: _BoundPS(ps, 0), 0)
    for _ in range(3):
        c2.commit(0, d)
    assert ps.num_updates == 6             # nothing silently dropped
    assert ps.stats()["dup_commits"] == 0
    np.testing.assert_allclose(ps.get_model()["w"], 6.0)


def test_resilient_client_reconnects_through_server_side_drops():
    """Real wire: injected server-side recv faults tear connections; the
    client reconnects and the run's folds stay exactly-once."""
    ps = SocketParameterServer({"w": np.zeros(4, np.float32)},
                               DownpourMerge(), 2, lease_timeout=5.0)
    ps.initialize()
    ps.start()
    plan = FaultPlan(seed=5, drop_recv=0.15, max_faults=30)
    try:
        clients = [
            ResilientPSClient(
                lambda i=i: ParameterServerClient("127.0.0.1", ps.port, i),
                i,
                # deadline-governed, not attempt-capped: which ops eat
                # the seeded drops depends on thread interleaving, and
                # under full-suite load one op can absorb 6+ in a row —
                # the default max_attempts=6 then fails a run the 30 s
                # deadline was meant to protect (seen flaking in tier-1)
                policy=RetryPolicy(max_attempts=100, base_delay=0.005,
                                   max_delay=0.05, deadline=30),
                heartbeat_interval=0.01,
            )
            for i in range(2)
        ]
        d = {"w": np.full(4, 0.5, np.float32)}
        errors = []

        def worker(i):
            try:
                for _ in range(20):
                    clients[i].pull()
                    clients[i].commit(i, d)
                    clients[i].maybe_heartbeat()
            except BaseException as e:  # pragma: no cover - fails below
                errors.append(e)

        with plan:
            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
        assert not errors, errors
        logical = sum(c.seq for c in clients)
        s = ps.stats()
        assert logical == 40
        assert ps.num_updates == s["commits"] == logical
        np.testing.assert_allclose(ps.get_model()["w"], 40 * 0.5)
        assert sum(c.retries for c in clients) > 0  # chaos actually bit
        assert s["heartbeats"] > 0
        for c in clients:
            c.close()
    finally:
        ps.stop()


# ---------------------------------------------------------------------------
# Supervisor recovery
# ---------------------------------------------------------------------------


def test_supervisor_restarts_dead_worker_to_completion(monkeypatch):
    """worker_restart_budget: a worker that dies once is relaunched and the
    run completes with every worker contributing — no tolerate_worker_
    failures downgrade needed."""
    from distkeras_tpu import DOWNPOUR
    from distkeras_tpu import workers as workers_mod

    orig = workers_mod.AsyncWorker._train
    died = []

    def dying_once(self, index, shard_cols, num_epoch, shuffle, seed):
        if self.worker_id == 1 and not died:
            died.append(1)
            raise RuntimeError("transient death")
        return orig(self, index, shard_cols, num_epoch, shuffle, seed)

    monkeypatch.setattr(workers_mod.AsyncWorker, "_train", dying_once)

    ds = blobs_dataset(n=512)
    t = DOWNPOUR(model_spec(), loss="sparse_softmax_cross_entropy",
                 worker_optimizer="sgd", learning_rate=0.05, num_workers=4,
                 batch_size=16, communication_window=2, num_epoch=2,
                 backend="ps", worker_restart_budget=2)
    with pytest.warns(UserWarning, match="restart 1/2"):
        t.train(ds)
    workers_seen = {r.get("worker") for r in t.get_history()
                    if "loss" in r}
    assert workers_seen == {0, 1, 2, 3}   # the restartee contributed
    assert t.resilience_stats_["restarts"] == 1
    assert final_loss(t) < 0.6


def test_supervisor_budget_exhaustion_defers_to_tolerance(monkeypatch):
    """A worker dying past its restart budget follows the pre-existing
    tolerance semantics: fatal by default, survivors finish when opted in."""
    from distkeras_tpu import DOWNPOUR
    from distkeras_tpu import workers as workers_mod

    orig = workers_mod.AsyncWorker._train

    def always_dying(self, index, shard_cols, num_epoch, shuffle, seed):
        if self.worker_id == 1:
            raise RuntimeError("hard death")
        return orig(self, index, shard_cols, num_epoch, shuffle, seed)

    monkeypatch.setattr(workers_mod.AsyncWorker, "_train", always_dying)

    ds = blobs_dataset(n=256)
    kw = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
              learning_rate=0.05, num_workers=2, batch_size=16,
              communication_window=2, num_epoch=1, backend="ps",
              worker_restart_budget=1)
    from distkeras_tpu.resilience import RestartBudgetExceeded

    with pytest.warns(UserWarning, match="restart 1/1"):
        with pytest.raises(RestartBudgetExceeded, match="hard death") as ei:
            DOWNPOUR(model_spec(), **kw).train(ds)
    assert isinstance(ei.value.__cause__, RuntimeError)
    t = DOWNPOUR(model_spec(), tolerate_worker_failures=True, **kw)
    with pytest.warns(UserWarning):
        t.train(ds)
    assert t.resilience_stats_["restarts"] == 1
    losses = [r["loss"] for r in t.get_history() if "loss" in r]
    assert losses and np.all(np.isfinite(losses))


# ---------------------------------------------------------------------------
# The chaos integration test (acceptance criterion)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cls_name", ["ADAG", "DOWNPOUR"])
def test_chaos_training_converges_with_exactly_once_folds(cls_name):
    """ADAG and DOWNPOUR under chaos — an injected worker kill plus socket
    drops and delays — must complete, converge below the no-fault run's
    first-epoch loss, prove via the commit-seqno oracle that no retried
    commit was double-folded, and surface heartbeat eviction + retry
    counts in ps.stats()."""
    import warnings

    import distkeras_tpu as dk

    cls = getattr(dk, cls_name)
    ds = blobs_dataset(n=1024)
    kw = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
              learning_rate=0.05, num_workers=4, batch_size=16,
              communication_window=2, num_epoch=2, backend="ps")

    # no-fault baseline: its FIRST-epoch loss is the convergence bar
    base = cls(model_spec(), **kw)
    base.train(ds, shuffle=True)
    first_epoch = float(np.mean(
        [r["loss"] for r in base.get_history()
         if "loss" in r and r.get("epoch") == 0]
    ))

    plan = FaultPlan(seed=11, drop_recv=0.04, delay=0.05, delay_s=0.002,
                     kill_at={1: 3}, max_faults=60)
    t = cls(model_spec(), **kw, ps_transport="socket",
            retry_policy=RetryPolicy(base_delay=0.005, max_delay=0.1,
                                     deadline=60),
            heartbeat_interval=0.05, lease_timeout=0.25,
            worker_restart_budget=2, worker_restart_delay=0.5,
            fault_plan=plan)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # restart/eviction warnings expected
        with plan:
            t.train(ds, shuffle=True)

    # (a) completed with the kill actually injected and recovered
    assert plan.stats()["kills"] == 1
    assert t.resilience_stats_["restarts"] >= 1
    # (b) converged: chaos-run final loss below the clean first-epoch loss
    assert final_loss(t) < first_epoch, (final_loss(t), first_epoch)
    # (c) the seqno oracle: folds applied == logical commits issued; every
    # replay the drops caused was deduplicated, never double-folded
    s = t.ps_stats_
    assert s["commits"] == t.resilience_stats_["logical_commits"]
    # chaos actually exercised the machinery (deterministic under the
    # seeded plan: drops are capped but plentiful at these op counts)
    assert t.resilience_stats_["retries"] > 0
    assert plan.stats()["drops"] > 0
    # (d) eviction and retry visibility: the killed worker's lease lapsed
    # during the 0.5 s restart cooldown (> 0.25 s lease) while survivors'
    # heartbeats drove expiry; its retries are in the registry totals
    assert s["evicted_workers"] >= 1
    assert s["heartbeats"] > 0
    assert s["dup_commits"] >= 0
    # every worker contributed post-chaos history
    workers_seen = {r.get("worker") for r in t.get_history() if "loss" in r}
    assert workers_seen == {0, 1, 2, 3}


def test_native_heartbeat_and_seqno_protocol_parity():
    """The C++ transport speaks the same HEARTBEAT/COMMIT_SEQ protocol:
    dedup, lease eviction, and the stats keys match the Python PS."""
    from distkeras_tpu.native import load_dkps

    if load_dkps() is None:
        pytest.skip("no C++ toolchain to build libdkps")
    from distkeras_tpu.native_ps import (
        NativePSClient,
        NativeSocketParameterServer,
    )

    center = {"w": np.zeros(5, np.float32)}
    ps = NativeSocketParameterServer(center, DownpourMerge(), 2,
                                     lease_timeout=0.15)
    ps.initialize()
    ps.start()
    try:
        c = NativePSClient("127.0.0.1", ps.port, 0, ps.spec)
        d = {"w": np.ones(5, np.float32)}
        c.commit(0, d, seq=1)
        c.commit(0, d, seq=1)              # replay → dup
        c.commit(0, d, seq=2)
        assert ps.num_updates == 2
        np.testing.assert_allclose(ps.get_model()["w"], 2.0)
        assert c.heartbeat(retries=7) is False   # registered
        assert c.heartbeat(retries=7) is True    # renewed
        s = ps.stats()
        assert s["commits"] == 2 and s["dup_commits"] == 1
        assert s["active_workers"] == 1 and s["worker_retries"] == 7
        time.sleep(0.3)
        s = ps.stats()                     # lease lapsed → evicted
        assert s["active_workers"] == 0 and s["evicted_workers"] == 1
        assert s["worker_retries"] == 7    # retained through eviction
        # clean deregister never counts as eviction
        c2 = NativePSClient("127.0.0.1", ps.port, 1, ps.spec)
        c2.heartbeat()
        c2.deregister()
        assert ps.stats()["evicted_workers"] == 1
        # key-set parity with the Python PS
        py = ParameterServer(center, DownpourMerge(), 2)
        assert set(ps.stats()) == set(py.stats())
        c.close(); c2.close()
    finally:
        ps.stop()


def test_resilient_training_inprocess_transport():
    """The wrapper is transport-agnostic: heartbeats + seqnos work on the
    in-process PS too (the oracle transport), end to end via the trainer."""
    from distkeras_tpu import DOWNPOUR

    ds = blobs_dataset(n=512)
    t = DOWNPOUR(model_spec(), loss="sparse_softmax_cross_entropy",
                 worker_optimizer="sgd", learning_rate=0.05, num_workers=2,
                 batch_size=16, communication_window=2, num_epoch=2,
                 backend="ps", retry_policy=RetryPolicy(),
                 heartbeat_interval=0.05)
    t.train(ds, shuffle=True)
    assert final_loss(t) < 0.6
    s = t.ps_stats_
    assert s["heartbeats"] >= 2            # both workers registered
    assert s["commits"] == t.resilience_stats_["logical_commits"]
    assert s["dup_commits"] == 0           # no faults, no replays


def test_resilience_knobs_rejected_off_ps_backend():
    from distkeras_tpu import ADAG

    with pytest.raises(ValueError, match="backend='ps' only"):
        ADAG(model_spec(), backend="collective",
             retry_policy=RetryPolicy())
    with pytest.raises(ValueError, match="backend='ps' only"):
        ADAG(model_spec(), backend="collective", worker_restart_budget=1)
