"""Live deployment (distkeras_tpu/deploy, ISSUE 16): weight streaming
from the training PS into the serving tier, the hot-swap version gate,
and router-orchestrated canary rollout with SLO-gated rollback.

The load-bearing oracles threaded through this file:

- every read replica's center is BIT-IDENTICAL to the training center at
  every snapshot version (one shared ``replay_record``, no drift);
- every served stream is bit-identical to a dense-cache ``generate()``
  oracle run at the version the stream was ADMITTED under — a swap never
  tears a batch (old+new weights in one decode step) and a refill
  re-serves the exact stream of the new version;
- a replica hard-killed mid-swap leaves no leaked KV blocks and no
  half-swapped state behind.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.deploy import (
    RolloutController,
    RolloutPolicy,
    SnapshotStore,
    WeightStreamer,
    watchtower_health,
)
from distkeras_tpu.models import generate, transformer_lm
from distkeras_tpu.parallel.merge_rules import ADAGMerge, DownpourMerge
from distkeras_tpu.parameter_servers import ParameterServer
from distkeras_tpu.serving import (
    GenerationClient,
    GenerationEngine,
    GenerationServer,
)

VOCAB, MAXLEN = 64, 64


@pytest.fixture(scope="module")
def lm():
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=32, heads=4,
                          depth=2, dtype=jnp.float32)
    p0, _ = spec.init_np(0)
    p1, _ = spec.init_np(1)
    return spec, p0, p1


def _oracle(spec, params, prompt, max_new):
    return generate(spec, params, prompt[None], max_new)[0, len(prompt):]


# -- WAL epoch marks ----------------------------------------------------------


def test_rec_epoch_roundtrip_and_replay():
    """REC_EPOCH frames round-trip the codec and replay into a monotone
    ``epoch_mark`` without touching the fold state; logs without any
    epoch record replay exactly as before (the record is advisory)."""
    from distkeras_tpu.resilience import wal

    blob = wal.encode_record(wal.REC_EPOCH, (3,))
    recs = list(wal.iter_records(blob))
    assert recs == [(wal.REC_EPOCH, (3,))]
    assert wal._REC_NAMES[wal.REC_EPOCH] == "epoch"

    state = {"center": {"w": np.zeros(2, np.float32)}, "num_updates": 5,
             "pull_versions": {}, "prev_pull_versions": {}, "last_seq": {}}
    wal.replay_record(state, wal.REC_EPOCH, (2,), DownpourMerge(), 1, None)
    assert state["epoch_mark"] == 2 and state["num_updates"] == 5
    wal.replay_record(state, wal.REC_EPOCH, (1,), DownpourMerge(), 1, None)
    assert state["epoch_mark"] == 2   # monotone: a late mark never rewinds


def test_ps_mark_epoch_logs_only_when_observable(tmp_path):
    """mark_epoch is a no-op without a WAL or replica (nothing would see
    it); with a WAL the mark lands in the log and recovery restores it."""
    from distkeras_tpu.resilience.wal import recover_ps_state

    ps = ParameterServer({"w": np.zeros(2, np.float32)}, DownpourMerge(), 1)
    ps.mark_epoch(0)   # no WAL, no replica: silently skipped

    ps = ParameterServer({"w": np.zeros(2, np.float32)}, DownpourMerge(), 1,
                         wal_dir=str(tmp_path))
    ps.pull(0)
    ps.commit(0, {"w": np.ones(2, np.float32)})
    ps.mark_epoch(4)
    ps.stop()
    state = recover_ps_state(str(tmp_path), DownpourMerge(), 1, None,
                             template={"w": np.zeros(2, np.float32)})
    assert state["epoch_mark"] == 4 and state["num_updates"] == 1


# -- deploy-lag accounting ----------------------------------------------------


def test_deploy_lag_stats_and_sharded_rollup():
    """deploy_lag_folds is 0 until a version is reported (training-only
    runs never look 'behind'), then num_updates − deploy_version; the
    sharded roll-up takes the min version (consistent cut) and the max
    lag (worst shard)."""
    from distkeras_tpu.sharding.group import aggregate_ps_stats

    ps = ParameterServer({"w": np.zeros(2, np.float32)}, DownpourMerge(), 1)
    for _ in range(3):
        ps.pull(0)
        ps.commit(0, {"w": np.ones(2, np.float32)})
    s = ps.stats()
    assert s["deploy_version"] == 0 and s["deploy_lag_folds"] == 0
    ps.report_deploy_version(2)
    ps.report_deploy_version(1)   # monotone: stale reports never rewind
    s = ps.stats()
    assert s["deploy_version"] == 2 and s["deploy_lag_folds"] == 1

    agg = aggregate_ps_stats([
        {"num_updates": 10, "deploy_version": 8, "deploy_lag_folds": 2,
         "commits": 10},
        {"num_updates": 10, "deploy_version": 4, "deploy_lag_folds": 6,
         "commits": 10},
    ])
    assert agg["deploy_version"] == 4 and agg["deploy_lag_folds"] == 6


def test_deploy_lag_rule_and_metrics_gauge():
    """The watchtower side of the satellite: DeployLagRule abstains with
    no deploy data, fires over the bound; the metrics schema exports the
    gauges so health_snapshot / remote scrapes carry them."""
    from distkeras_tpu.observability.metrics import _PS_SCHEMA
    from distkeras_tpu.observability.timeseries import TimeSeriesStore
    from distkeras_tpu.observability.watch import (
        DeployLagRule,
        Watchdog,
        default_rules,
    )

    assert any(k == "deploy_lag_folds" for k, _, _, _ in _PS_SCHEMA)
    assert any(r.kind == "deploy_lag" for r in default_rules())

    store = TimeSeriesStore()
    wd = Watchdog(store, rules=[DeployLagRule(bound=100.0)])
    wd.evaluate(now=1.0)
    assert not wd.active                    # no data: abstain
    store.sample("ps.deploy_lag_folds", 2.0, 500.0, "gauge")
    wd.evaluate(now=2.0)
    assert not wd.active                    # lag but no deploy_version yet
    store.sample("ps.deploy_version", 3.0, 7.0, "gauge")
    wd.evaluate(now=3.0)
    assert any(a["kind"] == "deploy_lag" for a in wd.active.values())
    store.sample("ps.deploy_lag_folds", 4.0, 10.0, "gauge")
    wd.evaluate(now=4.0)
    assert not wd.active                    # caught up: resolves


# -- snapshot store -----------------------------------------------------------


def test_snapshot_store_monotone_prune_subscribe():
    store = SnapshotStore(keep=2)
    seen = []
    store.subscribe(lambda s: seen.append(s.version))
    t = {"w": np.ones(2, np.float32)}
    assert store.publish(10, t)
    assert not store.publish(10, t)         # equal version: dropped
    assert not store.publish(5, t)          # older: dropped
    assert store.publish(20, t) and store.publish(30, t)
    assert store.versions() == [20, 30]     # keep=2 pruned v10
    assert store.latest().version == 30 and store.get(20) is not None
    assert seen == [10, 20, 30]
    with pytest.raises(ValueError, match="keep"):
        SnapshotStore(keep=0)


def test_epoch_snapshot_writes_elastic_checkpoint(tmp_path):
    """Satellite 1: an epoch-boundary snapshot with checkpoint_dir set
    lands on disk in run_async_training's resume payload shape —
    workers=[] routes resume through the elastic center-only path."""
    from distkeras_tpu.checkpoint import restore_checkpoint

    store = SnapshotStore(keep=4, checkpoint_dir=str(tmp_path))
    tree = {"w": np.arange(4, dtype=np.float32)}
    store.publish(10, tree, epoch=None)     # fold-count cut: no checkpoint
    assert store.checkpoints_written == 0
    store.publish(25, tree, epoch=3)        # epoch cut: checkpointed
    assert store.checkpoints_written == 1
    payload, step = restore_checkpoint(str(tmp_path))
    assert step == 25 and payload["epoch"] == 3
    assert payload["workers"] == [] and payload["num_updates"] == 25
    np.testing.assert_array_equal(payload["center"]["w"], tree["w"])


# -- weight streaming ---------------------------------------------------------


def _drain_to(streamer, version, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if streamer.stats()["latest_version"] >= version:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"streamer never reached v{version}: {streamer.stats()}"
    )


def test_streamer_cuts_at_folds_and_epochs_bit_identical():
    """Fold-count cuts at snapshot_every multiples, an epoch mark always
    cuts (carrying the epoch), every snapshot bit-identical to the
    training center at that version, and the published versions flow
    back into the PS's deploy-lag accounting."""
    rule = ADAGMerge()
    ps = ParameterServer({"w": np.zeros(4, np.float32)}, rule, 2)
    st = WeightStreamer(ADAGMerge(), 2, snapshot_every=5)
    st.attach_to(ps)
    try:
        d = {"w": np.full(4, 0.5, np.float32)}
        for _ in range(12):
            ps.pull(0)
            ps.commit(0, d)
        ps.mark_epoch(0)
        _drain_to(st, 12)
        assert st.store.versions() == [5, 10, 12]
        assert st.store.get(12).epoch == 0       # the epoch cut
        assert st.store.get(10).epoch is None    # a fold-count cut
        np.testing.assert_array_equal(
            st.store.latest().tree["w"], ps.get_model()["w"]
        )
        s = ps.stats()
        assert s["deploy_version"] == 12 and s["deploy_lag_folds"] == 0
        rep = st.stats()["replicas"][0]
        assert rep["streaming"] and rep["num_updates"] == 12
    finally:
        st.close()


def test_streamer_chain_shares_one_replica_slot():
    """Two serving hosts chain off the PS's single replica slot: the
    downstream streamer sees the same records and publishes the same
    bits, and a second direct attach is refused (the slot is taken)."""
    ps = ParameterServer({"w": np.zeros(4, np.float32)}, ADAGMerge(), 2)
    s1 = WeightStreamer(ADAGMerge(), 2, snapshot_every=4)
    s2 = WeightStreamer(ADAGMerge(), 2, snapshot_every=4)
    s1.chain_to(s2)
    s1.attach_to(ps)
    try:
        with pytest.raises(ValueError, match="slot is taken"):
            WeightStreamer(ADAGMerge(), 2).attach_to(ps)
        d = {"w": np.ones(4, np.float32)}
        for _ in range(8):
            ps.pull(1)
            ps.commit(1, d)
        _drain_to(s1, 8)
        _drain_to(s2, 8)
        np.testing.assert_array_equal(
            s1.store.latest().tree["w"], s2.store.latest().tree["w"]
        )
        assert s2.store.versions() == [4, 8]
    finally:
        s1.close()
        s2.close()


def test_streamer_sharded_consistent_cut():
    """Sharded center: the streamer subscribes to every shard's stream
    and publishes only when ALL shards were captured at the same version
    — the assembled snapshot equals the group's joined center, bitwise."""
    from distkeras_tpu.sharding.group import ShardedPSGroup

    tree = {"a": np.zeros(6, np.float32), "b": np.zeros((3, 2), np.float32)}
    group = ShardedPSGroup(tree, DownpourMerge(), 1, num_shards=2,
                           transport="inprocess")
    group.initialize()
    group.start()
    st = WeightStreamer(DownpourMerge(), 1, plan=group.plan,
                        snapshot_every=3)
    st.attach_to(group)
    try:
        c = group.make_client(0)
        d = {"a": np.full(6, 0.25, np.float32),
             "b": np.full((3, 2), -0.5, np.float32)}
        for _ in range(6):
            c.pull()
            c.commit(0, d)
        _drain_to(st, 6)
        assert st.store.versions() == [3, 6]
        snap = st.store.latest()
        center = group.get_model()
        for k in tree:
            np.testing.assert_array_equal(snap.tree[k], center[k])
        s = group.stats()
        assert s["deploy_version"] == 6 and s["deploy_lag_folds"] == 0
    finally:
        st.close()
        group.stop()


# -- the hot-swap version gate ------------------------------------------------


def test_swap_refill_streams_bit_identical_to_new_version(lm):
    """Property test (the no-torn-batch oracle): a refill swap mid-batch
    frees every in-flight row's blocks and re-prefills under the new
    weights — every served stream (greedy AND seeded-sampled) is then
    bit-identical to a generate() oracle at the version the request was
    (re)admitted under."""
    spec, p0, p1 = lm
    rng = np.random.default_rng(29)
    eng = GenerationEngine(spec, p0, max_batch=3, block_size=8,
                           model_version=1)
    prompts = [rng.integers(0, VOCAB, (n,)).astype(np.int32)
               for n in (8, 13, 6, 11)]
    reqs = [eng.submit(prompts[0], max_new_tokens=12),
            eng.submit(prompts[1], max_new_tokens=12),
            eng.submit(prompts[2], max_new_tokens=12,
                       temperature=0.8, top_k=8, seed=5),
            eng.submit(prompts[3], max_new_tokens=12)]
    for _ in range(3):
        eng.step()          # rows admitted, tokens emitted on v1 weights
    eng.swap_params(p1, 2, policy="refill")
    eng.run_until_idle()
    params_by = {1: p0, 2: p1}
    for p, r in zip(prompts, reqs):
        assert r.state == "done" and r.model_version == 2
        params = params_by[r.model_version]
        if r.temperature == 0.0:
            np.testing.assert_array_equal(
                r.result(0), _oracle(spec, params, p, 12)
            )
        else:
            # deterministic per (seed, position): the refilled sampled
            # stream equals a fresh same-seed run at the new version
            eng2 = GenerationEngine(spec, params, max_batch=1, block_size=8)
            r2 = eng2.submit(p, max_new_tokens=12, temperature=0.8,
                             top_k=8, seed=5)
            eng2.run_until_idle()
            np.testing.assert_array_equal(r.result(0), r2.result(0))
    s = eng.stats()
    assert s["swaps"] == 1 and s["refilled"] >= 1
    assert s["model_version"] == 2 and s["blocks_in_use"] == 0


def test_swap_drain_finishes_old_batch_then_swaps(lm):
    """Drain policy: in-flight rows finish on the OLD weights (their
    admitted version), admission holds the door, and queued requests run
    on the NEW weights after the gate — both halves oracle-exact."""
    spec, p0, p1 = lm
    rng = np.random.default_rng(31)
    pa = rng.integers(0, VOCAB, (9,)).astype(np.int32)
    pb = rng.integers(0, VOCAB, (7,)).astype(np.int32)
    eng = GenerationEngine(spec, p0, max_batch=2, block_size=8,
                           model_version=1)
    ra = eng.submit(pa, max_new_tokens=10)
    for _ in range(3):
        eng.step()
    eng.swap_params(p1, 2, policy="drain")
    rb = eng.submit(pb, max_new_tokens=10)   # queued behind the gate
    eng.run_until_idle()
    assert ra.model_version == 1 and rb.model_version == 2
    np.testing.assert_array_equal(ra.result(0), _oracle(spec, p0, pa, 10))
    np.testing.assert_array_equal(rb.result(0), _oracle(spec, p1, pb, 10))
    s = eng.stats()
    assert s["refilled"] == 0 and s["model_version"] == 2
    assert s["blocks_in_use"] == 0
    with pytest.raises(ValueError, match="policy"):
        eng.swap_params(p1, 3, policy="nope")


def test_swap_applies_while_engine_idle(lm):
    """A staged swap must not wait for traffic: the scheduler loop wakes
    and applies it with an empty batch (rollback repins idle replicas)."""
    spec, p0, p1 = lm
    eng = GenerationEngine(spec, p0, max_batch=2, block_size=8,
                           model_version=7)
    eng.start()
    try:
        eng.swap_params(p1, 3, policy="drain")   # version DECREASES: rollback
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and eng.stats()["model_version"] != 3:
            time.sleep(0.02)
        assert eng.stats()["model_version"] == 3
    finally:
        eng.stop(drain=False)


# -- rollout policy (pure state machine) --------------------------------------


def test_rollout_policy_canary_bake_promote():
    pol = RolloutPolicy(canary_fraction=0.5, bake_s=2.0, green_checks=2,
                        red_checks=1, cooldown_s=5.0)
    assert pol.observe(0.0, None, True, False) == []        # nothing staged
    acts = pol.observe(1.0, 4, True, False)
    assert acts == [{"t": 1.0, "action": "canary", "state": "canary",
                     "version": 4, "fraction": 0.5}]
    assert pol.observe(2.0, 4, True, False) == []           # still baking
    assert pol.observe(3.5, 4, True, False) == []           # 1st green check
    acts = pol.observe(4.0, 4, True, False)                 # 2nd: promote
    assert acts[0]["action"] == "promote" and acts[0]["version"] == 4
    assert pol.state == "idle" and pol.version == 4
    # stale candidate (<= promoted baseline) never restarts a rollout
    assert pol.observe(20.0, 4, True, False) == []
    assert [d["action"] for d in pol.decisions] == ["canary", "promote"]


def test_rollout_policy_slo_rollback_and_cooldown():
    pol = RolloutPolicy(canary_fraction=0.25, bake_s=0.0, green_checks=1,
                        red_checks=2, cooldown_s=10.0)
    pol.observe(0.0, 2, True, False)
    assert pol.state == "canary"
    assert pol.observe(1.0, 2, False, True) == []     # 1st red: hysteresis
    acts = pol.observe(2.0, 2, False, True)           # 2nd consecutive red
    assert acts[0]["action"] == "rollback" and acts[0]["to"] == 0
    assert pol.state == "idle" and pol.version == 0
    # cooldown: the same candidate cannot re-canary immediately
    assert pol.observe(3.0, 2, True, False) == []
    acts = pol.observe(13.0, 2, True, False)
    assert acts and acts[0]["action"] == "canary"
    # a non-green (non-SLO) alert blocks promotion but never rolls back
    assert pol.observe(14.0, 2, False, False) == []
    assert pol.state == "canary"


def test_rollout_policy_validates():
    for kw in ({"canary_fraction": 0.0}, {"canary_fraction": 1.5},
               {"bake_s": -1}, {"green_checks": 0}, {"red_checks": 0},
               {"cooldown_s": -0.1}):
        with pytest.raises(ValueError):
            RolloutPolicy(**kw)


def test_watchtower_health_adapter():
    class FakeDog:
        active = {}

    assert watchtower_health(FakeDog()) == (True, False)
    FakeDog.active = {"r1": {"kind": "loss_stall"}}
    assert watchtower_health(FakeDog()) == (False, False)
    FakeDog.active = {"r1": {"kind": "serving_slo"}}
    assert watchtower_health(FakeDog()) == (False, True)


# -- serving fleet helpers ----------------------------------------------------


def _serve_replica(spec, params, version, store, directory, key):
    eng = GenerationEngine(spec, params, max_batch=2, block_size=8,
                           model_version=version)
    srv = GenerationServer(eng, poll_interval=0.02)
    srv.snapshots = store
    srv.start()
    srv.register_with(directory, key=key, ttl=5.0)
    return srv


def _fleet_versions(router):
    router.refresh(force=True)
    return router.replica_versions()


def _wait_fleet(router, want, timeout=15.0):
    """Wait until the advertised version map equals ``want`` (renewer
    republishes within ttl/3 of a swap)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _fleet_versions(router) == want:
            return
        time.sleep(0.1)
    raise AssertionError(
        f"fleet never advertised {want}: {_fleet_versions(router)}"
    )


# -- chaos: hard kill mid-swap ------------------------------------------------


def test_chaos_hard_kill_replica_mid_swap(lm):
    """Seeded chaos at the swap boundary: two replicas serve routed
    traffic; one is HARD-killed with a refill swap staged and requests
    in flight. In-flight routed requests fail over and complete on the
    survivor (bit-identical to its version's oracle), the victim frees
    every KV block on the way down (no leak, no torn batch), and the
    router's next refresh drops the corpse."""
    from distkeras_tpu.directory import DirectoryServer
    from distkeras_tpu.directory.router import RoutedGenerationClient

    spec, p0, p1 = lm
    store = SnapshotStore(keep=4)
    store.publish(1, p0)
    store.publish(2, p1)
    dsrv = DirectoryServer(default_ttl=2.0)
    dsrv.initialize()
    dsrv.start()
    seeds = [(dsrv.host, dsrv.port)]
    srv_a = _serve_replica(spec, p0, 1, store, seeds, "rep-a")
    srv_b = _serve_replica(spec, p0, 1, store, seeds, "rep-b")
    router = RoutedGenerationClient(directory=seeds, refresh_interval=0.2)
    rng = np.random.default_rng(17)
    results, errs = {}, []

    def client(i):
        try:
            p = rng.integers(0, VOCAB, (6 + i,)).astype(np.int32)
            results[i] = (p, router.generate(p, max_new_tokens=10))
        except Exception as e:  # surfaced below
            errs.append((i, e))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(6)]
    try:
        for t in threads:
            t.start()
        # stage a refill swap on the victim, then kill it mid-swap: the
        # staged swap + any in-flight rows die with the process image
        GenerationClient(srv_a.host, srv_a.port).deploy_activate(
            2, policy="refill")
        srv_a.stop(drain=False, timeout=5.0)
        for t in threads:
            t.join(60)
        assert not errs, errs
        assert len(results) == 6
        for p, toks in results.values():
            # every stream completed somewhere; whichever replica served
            # it was at v1 (p0) or v2 (p1) whole — never a mix
            o1, o2 = (_oracle(spec, p0, p, 10), _oracle(spec, p1, p, 10))
            assert (np.array_equal(toks, o1) or np.array_equal(toks, o2))
        # the victim died clean: no leaked blocks, nothing half-swapped
        va = srv_a.engine.stats()
        assert va["blocks_in_use"] == 0 and va["active"] == 0
        sb = srv_b.engine.stats()
        assert sb["blocks_in_use"] == 0
        # the corpse ages out of the ring
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            router.refresh(force=True)
            if set(router.replicas) == {"rep-b"}:
                break
            time.sleep(0.2)
        assert set(router.replicas) == {"rep-b"}
    finally:
        router.close()
        srv_b.stop(drain=False)
        srv_a.stop(drain=False)
        dsrv.stop()


# -- the end-to-end acceptance ------------------------------------------------


def test_e2e_stream_canary_promote_then_slo_rollback(lm):
    """The ISSUE 16 acceptance path, in-process: async training (ADAG
    merge rule) folds live while a WeightStreamer materializes versions;
    two directory-registered replicas serve; a canary rollout promotes
    on watchdog-green; a second leg with an injected latency fault rolls
    back on the firing ServingSLORule. Every served stream bit-identical
    to the oracle at its replica's admitted version, deploy_lag_folds
    bounded, every transition journaled."""
    from distkeras_tpu.directory import DirectoryServer
    from distkeras_tpu.directory.router import RoutedGenerationClient
    from distkeras_tpu.observability.timeseries import TimeSeriesStore
    from distkeras_tpu.observability.watch import (
        ServingSLORule,
        SLOClass,
        Watchdog,
    )

    spec, p0, _ = lm
    rule = ADAGMerge()
    ps = ParameterServer(p0, rule, 2)
    st = WeightStreamer(ADAGMerge(), 2, snapshot_every=4)
    st.attach_to(ps)

    def train(folds):
        # two async workers committing tiny deltas: live ADAG training
        def worker(wid, n):
            rng = np.random.default_rng(wid)
            for _ in range(n):
                center = ps.pull(wid)
                delta = jax.tree.map(
                    lambda a: (rng.standard_normal(a.shape) * 1e-3
                               ).astype(a.dtype),
                    center,
                )
                ps.commit(wid, delta)
        ts = [threading.Thread(target=worker, args=(w, folds // 2))
              for w in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)

    dsrv = DirectoryServer(default_ttl=3.0)
    dsrv.initialize()
    dsrv.start()
    seeds = [(dsrv.host, dsrv.port)]
    router = None
    servers = []
    try:
        train(8)
        _drain_to(st, 8)
        va = st.store.versions()[0]
        base = st.store.get(va)
        servers = [
            _serve_replica(spec, base.tree, va, st.store, seeds, f"rep-{i}")
            for i in range(2)
        ]
        router = RoutedGenerationClient(directory=seeds,
                                        refresh_interval=0.2)
        _wait_fleet(router, {"rep-0": va, "rep-1": va})

        # watchtower: the serving SLO is the rollback trigger; feeding
        # the series by hand makes green/red deterministic
        tstore = TimeSeriesStore()
        wd = Watchdog(tstore, rules=[
            ServingSLORule(slo={"default": SLOClass(p99_ms=500.0)}),
        ])

        def observe(p99_ms, now):
            tstore.sample("serve.lat.default.p99_ms", now, p99_ms)
            wd.evaluate(now=now)

        by_key = {f"rep-{i}": srv for i, srv in enumerate(servers)}

        def activate(key, version):
            c = GenerationClient(by_key[key].host, by_key[key].port)
            try:
                return bool(c.deploy_activate(version,
                                              policy="refill")["ok"])
            finally:
                c.close()

        ctrl = RolloutController(
            router, activate, lambda: watchtower_health(wd),
            policy=RolloutPolicy(canary_fraction=0.5, bake_s=0.0,
                                 green_checks=1, red_checks=1,
                                 cooldown_s=0.0),
        )

        def served_bit_identical():
            # each replica, at whatever version it advertises, serves
            # the oracle stream of that version's snapshot — streaming
            # kept every materialized center bit-identical to training
            rng = np.random.default_rng(5)
            for key, srv in by_key.items():
                c = GenerationClient(srv.host, srv.port)
                try:
                    v = c.deploy_status()["model_version"]
                    p = rng.integers(0, VOCAB, (8,)).astype(np.int32)
                    toks = c.generate(p, max_new_tokens=8)
                finally:
                    c.close()
                np.testing.assert_array_equal(
                    toks, _oracle(spec, st.store.get(v).tree, p, 8),
                    err_msg=f"{key} tore the stream at v{v}",
                )

        # ---- leg 1: train on, canary the new version, promote on green
        train(8)
        _drain_to(st, 16)
        vb = st.store.versions()[-1]
        assert vb > va
        ctrl.begin(vb)
        observe(50.0, 1.0)                       # healthy latency: green
        acts = ctrl.step(1.0)
        assert [a["action"] for a in acts] == ["canary"]
        assert len(ctrl.canary_keys) == 1        # 50% of 2 replicas
        canary, = ctrl.canary_keys
        rest, = set(by_key) - {canary}
        _wait_fleet(router, {canary: vb, rest: va})
        served_bit_identical()                   # mixed-version fleet
        observe(60.0, 2.0)
        acts = ctrl.step(2.0)
        assert [a["action"] for a in acts] == ["promote"]
        _wait_fleet(router, {"rep-0": vb, "rep-1": vb})
        served_bit_identical()

        # ---- leg 2: next candidate canaries, injected latency fires
        # the SLO, the controller rolls the canary back to vb
        train(8)
        _drain_to(st, 24)
        vc = st.store.versions()[-1]
        assert vc > vb
        ctrl.begin(vc)
        observe(70.0, 3.0)
        assert [a["action"] for a in ctrl.step(3.0)] == ["canary"]
        canary2, = ctrl.canary_keys
        observe(5000.0, 4.0)                     # injected latency fault
        assert any(a["kind"] == "serving_slo" for a in wd.active.values())
        acts = ctrl.step(4.0)
        assert [a["action"] for a in acts] == ["rollback"]
        _wait_fleet(router, {"rep-0": vb, "rep-1": vb})
        served_bit_identical()

        # routed traffic over the (now settled) fleet: streams complete
        # and the per-version routing split lands in router stats
        rng = np.random.default_rng(23)
        for _ in range(4):
            p = rng.integers(0, VOCAB, (7,)).astype(np.int32)
            toks = router.generate(p, max_new_tokens=6)
            np.testing.assert_array_equal(
                toks, _oracle(spec, st.store.get(vb).tree, p, 6)
            )
        rs = router.stats()
        assert sum(rs["routed_by_version"].values()) >= 4
        assert rs["routed_by_version"].get(vb, 0) >= 4
        assert set(rs["replica_versions"].values()) == {vb}

        # the journal CI uploads: one record per executed transition
        assert [j["action"] for j in ctrl.journal] == [
            "canary", "promote", "canary", "rollback",
        ]
        assert all("keys" in j and "activated" in j for j in ctrl.journal)
        # deploy lag stayed bounded: training is 24 folds in, serving
        # materialized through v24, gap under one snapshot interval
        assert ps.stats()["deploy_lag_folds"] <= st.snapshot_every
    finally:
        if router is not None:
            router.close()
        for srv in servers:
            srv.stop(drain=False)
        st.close()
        dsrv.stop()


# -- trainer integration ------------------------------------------------------


def test_trainer_deploy_streamer_knob_elastic_epoch_checkpoint(tmp_path):
    """The trainer-side knob: an elastic ADAG run with deploy_streamer=
    streams every fold into the snapshot store, the elastic epoch
    boundary (ShardAssigner retirement → mark_epoch → REC_EPOCH) cuts an
    epoch snapshot, and the store's checkpoint_dir gets the resumable
    elastic epoch-barrier checkpoint that closes ROADMAP item 2."""
    import distkeras_tpu as dk
    from distkeras_tpu.checkpoint import restore_checkpoint
    from tests.test_trainers import blobs_dataset, model_spec

    st = WeightStreamer(ADAGMerge(), 2, snapshot_every=0,
                        checkpoint_dir=str(tmp_path / "deploy-ckpt"))
    ds = blobs_dataset(n=512)
    t = dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", learning_rate=0.05, num_workers=2,
                batch_size=16, communication_window=2, num_epoch=2,
                backend="ps", elastic=True, deploy_streamer=st)
    try:
        t.train(ds)
        # both epoch boundaries marked → two epoch cuts, both durable
        _drain_to(st, 1)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline \
                and st.store.checkpoints_written < 2:
            time.sleep(0.05)
        assert st.store.checkpoints_written == 2
        snaps = [st.store.get(v) for v in st.store.versions()]
        # epoch marks are monotone (max) and the retirement callbacks
        # race outside the assigner lock, so an inverted pair labels
        # both cuts epoch 1 — the barrier itself is always epoch 1
        assert all(s.epoch in (0, 1) for s in snaps)
        assert snaps[-1].epoch == 1
        payload, step = restore_checkpoint(str(tmp_path / "deploy-ckpt"))
        assert payload["workers"] == [] and payload["epoch"] == 1
        assert payload["num_updates"] == step == st.store.latest().version
        # resume path: center-only elastic restart consumes this payload
        with pytest.warns(UserWarning, match="elastic resume"):
            from distkeras_tpu.checkpoint import warn_elastic_resume

            warn_elastic_resume(len(payload["workers"]), 2)
    finally:
        st.close()

    with pytest.raises(ValueError, match="deploy_streamer"):
        dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                num_workers=2, backend="ps", ps_transport="socket",
                ps_host="10.0.0.1", deploy_streamer=object())
