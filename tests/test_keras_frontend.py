"""Keras 3 frontend — the reference's primary user contract.

Reference users handed a Keras model straight to a trainer (reference
``distkeras/trainers.py :: Trainer.__init__(keras_model, ...)``) and got the
same model back with trained weights. These tests pin that contract on the
8-fake-device CPU mesh: training through ``from_keras``/``stateless_call``,
weight write-back into the live model, and the ``serialize_keras_model``
round-trip from reference ``distkeras/utils.py``.
"""

import numpy as np
import pytest

keras = pytest.importorskip("keras")

from distkeras_tpu import ADAG, AEASGD
from distkeras_tpu.utils import deserialize_keras_model, serialize_keras_model
from tests.test_trainers import blobs_dataset, final_loss, initial_loss


def make_keras_mlp(dim=16, classes=4, seed=0):
    keras.utils.set_random_seed(seed)
    return keras.Sequential([
        keras.layers.Input((dim,)),
        keras.layers.Dense(32, activation="relu"),
        keras.layers.Dense(classes),
    ])


def test_keras_model_through_adag_on_mesh():
    ds = blobs_dataset(n=2048)
    model = make_keras_mlp()
    before = [np.copy(w) for w in model.get_weights()]
    t = ADAG(model, loss="sparse_softmax_cross_entropy",
             worker_optimizer="sgd", learning_rate=0.1, num_workers=8,
             batch_size=32, communication_window=2, num_epoch=3)
    out = t.train(ds, shuffle=True)
    # the SAME model object is returned, with trained weights written back
    assert out is model
    after = model.get_weights()
    assert any(not np.allclose(a, b) for a, b in zip(before, after))
    assert final_loss(t) < 0.5
    assert final_loss(t) < initial_loss(t) / 2
    # the live Keras model predicts with the trained weights
    preds = np.argmax(model.predict(ds["features"][:512], verbose=0), -1)
    acc = float(np.mean(preds == ds["label"][:512]))
    assert acc > 0.85, acc


def test_keras_model_through_elastic_trainer():
    ds = blobs_dataset(n=2048)
    model = make_keras_mlp()
    t = AEASGD(model, loss="sparse_softmax_cross_entropy",
               worker_optimizer="sgd", learning_rate=0.05, rho=0.5,
               num_workers=8, batch_size=32, communication_window=8,
               num_epoch=3)
    out = t.train(ds, shuffle=True)
    assert out is model
    assert final_loss(t) < 0.6
    preds = np.argmax(model.predict(ds["features"][:512], verbose=0), -1)
    assert float(np.mean(preds == ds["label"][:512])) > 0.8


def test_serialize_keras_model_roundtrip():
    model = make_keras_mlp(seed=4)
    payload = serialize_keras_model(model)
    clone = deserialize_keras_model(payload)
    x = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
    np.testing.assert_allclose(
        model.predict(x, verbose=0), clone.predict(x, verbose=0), atol=1e-5
    )


def test_trained_keras_model_survives_serde():
    """Train → serialize → deserialize → identical predictions (the
    reference's model-shipping path)."""
    ds = blobs_dataset(n=1024)
    model = make_keras_mlp()
    ADAG(model, loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
         learning_rate=0.1, num_workers=4, batch_size=32,
         communication_window=2, num_epoch=2).train(ds)
    clone = deserialize_keras_model(serialize_keras_model(model))
    x = ds["features"][:64]
    np.testing.assert_allclose(
        model.predict(x, verbose=0), clone.predict(x, verbose=0), atol=1e-5
    )


def test_distkeras_alias_hasattr_contract():
    """getattr with default / hasattr must not leak ImportError."""
    import distkeras

    assert not hasattr(distkeras, "definitely_not_a_module")
    assert getattr(distkeras, "definitely_not_a_module", None) is None
    # real late-bound module still resolves
    assert hasattr(distkeras, "networking")


def test_keras_batchnorm_model_trains_and_stats_move():
    """The reference contract covers stateful Keras models too: BatchNorm
    moving statistics ride the non-trainable state path and are written
    back into the live model after training."""
    import keras

    from distkeras_tpu import ADAG
    from distkeras_tpu.data import Dataset

    model = keras.Sequential([
        keras.layers.Input((16,)),
        keras.layers.Dense(32, activation="relu"),
        keras.layers.BatchNormalization(),
        keras.layers.Dense(4),
    ])
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 16)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    ds = Dataset({"features": x, "label": y})
    t = ADAG(model, loss="sparse_softmax_cross_entropy",
             worker_optimizer="adam", learning_rate=5e-3, num_workers=4,
             batch_size=16, communication_window=2, num_epoch=8)
    out = t.train(ds, shuffle=True)
    assert out is model
    bn = model.layers[1]
    assert np.any(np.abs(np.asarray(bn.moving_mean)) > 1e-3)
    assert np.any(np.abs(np.asarray(bn.moving_variance) - 1.0) > 1e-3)
    preds = np.argmax(model.predict(x, verbose=0), axis=-1)
    assert np.mean(preds == y) > 0.7


def test_keras_dropout_model_trains_and_infers_deterministically():
    """Reference-era Keras models carry Dropout layers (the upstream MNIST
    examples did); they must train through the trainers — the Keras seed-
    generator state rides the non-trainable path — with dropout ACTIVE in
    training mode and OFF at inference."""
    import keras

    from distkeras_tpu.data import Dataset
    from distkeras_tpu.model import from_keras

    model = keras.Sequential([
        keras.layers.Input((16,)),
        keras.layers.Dense(32, activation="relu"),
        keras.layers.Dropout(0.5),
        keras.layers.Dense(4),
    ])
    spec = from_keras(model)
    params, state = spec.init(None)
    x = np.random.default_rng(1).normal(size=(8, 16)).astype(np.float32)
    # training mode is stochastic (different masks as the seed state
    # advances), inference is deterministic
    o1, s1 = spec.apply(params, state, x, training=True)
    o2, _ = spec.apply(params, s1, x, training=True)
    assert not np.allclose(np.asarray(o1), np.asarray(o2))
    e1, _ = spec.apply(params, state, x, training=False)
    e2, _ = spec.apply(params, state, x, training=False)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))

    rng = np.random.default_rng(0)
    xs = rng.normal(size=(256, 16)).astype(np.float32)
    ys = (xs[:, 0] > 0).astype(np.int32)
    ds = Dataset({"features": xs, "label": ys})
    t = ADAG(model, loss="sparse_softmax_cross_entropy",
             worker_optimizer="adam", learning_rate=5e-3, num_workers=4,
             batch_size=16, communication_window=2, num_epoch=8)
    out = t.train(ds, shuffle=True)
    assert out is model
    preds = np.argmax(model.predict(xs, verbose=0), axis=-1)
    assert np.mean(preds == ys) > 0.7
