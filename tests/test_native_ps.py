"""Native (C++) parameter-server transport: build, folds, wire, training.

The native PS (``distkeras_tpu/native_ps.py`` + ``native/dkps.cpp``) must be
semantically interchangeable with the Python socket PS — same fold math per
merge rule, same staleness bookkeeping, same trainer surface — while moving
weights as raw float32 frames with no pickle and no GIL on the wire path.
Every test here pins the native path against the Python PS oracle
(``parameter_servers.ParameterServer``) the way the socket tests pin it.
"""

import socket
import struct
import threading

import numpy as np
import pytest

from distkeras_tpu.native import load_dkps
from distkeras_tpu.parallel.merge_rules import (
    ADAGMerge,
    DownpourMerge,
    DynSGDMerge,
    ElasticAverageMerge,
)
from distkeras_tpu.parameter_servers import ParameterServer
from tests.test_trainers import blobs_dataset, final_loss, model_spec

pytestmark = pytest.mark.skipif(
    load_dkps() is None, reason="no C++ toolchain to build libdkps"
)


def make_server(center, rule, num_workers, ema_decay=None):
    from distkeras_tpu.native_ps import NativeSocketParameterServer

    ps = NativeSocketParameterServer(center, rule, num_workers,
                                     ema_decay=ema_decay)
    ps.initialize()
    ps.start()
    return ps


def make_client(ps, worker_id):
    from distkeras_tpu.native_ps import NativePSClient

    return NativePSClient("127.0.0.1", ps.port, worker_id, ps.spec)


def test_flatspec_roundtrip_mixed_shapes_dtypes():
    from distkeras_tpu.native_ps import FlatSpec

    tree = {
        "dense": {"kernel": np.arange(12, dtype=np.float32).reshape(3, 4),
                  "bias": np.ones(4, np.float32)},
        "scale": np.float32(2.5),
        "emb": np.random.default_rng(0).normal(size=(5, 2)).astype(np.float32),
    }
    spec = FlatSpec(tree)
    vec = spec.flatten(tree)
    assert vec.dtype == np.float32 and vec.shape == (12 + 4 + 1 + 10,)
    back = spec.unflatten(vec)
    import jax

    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(tree)):
        assert np.asarray(a).dtype == np.asarray(b).dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("rule_factory", [
    lambda: ADAGMerge(),
    lambda: DownpourMerge(),
    lambda: ElasticAverageMerge(alpha=0.05),
    lambda: DynSGDMerge(),
], ids=["adag", "downpour", "elastic", "dynsgd"])
def test_native_fold_matches_python_ps(rule_factory):
    """Identical pull/commit sequences fold to the same center on both
    transports (the single-oracle contract the socket PS already honors)."""
    rng = np.random.default_rng(3)
    center = {"w": rng.normal(size=(4, 3)).astype(np.float32),
              "b": rng.normal(size=(3,)).astype(np.float32)}
    W = 3
    oracle = ParameterServer(center, rule_factory(), W)
    ps = make_server(center, rule_factory(), W)
    try:
        clients = [make_client(ps, i) for i in range(W)]
        script = [(0, "pull"), (1, "pull"), (1, "commit"), (0, "commit"),
                  (2, "pull"), (2, "commit"), (0, "pull"), (0, "commit")]
        for step, (wid, action) in enumerate(script):
            if action == "pull":
                got = clients[wid].pull()
                want = oracle.pull(wid)
                for a, b in zip(np.ravel(got["w"]), np.ravel(want["w"])):
                    np.testing.assert_allclose(a, b, rtol=1e-6)
            else:
                payload = {
                    "w": rng.normal(size=(4, 3)).astype(np.float32),
                    "b": rng.normal(size=(3,)).astype(np.float32),
                }
                clients[wid].commit(wid, payload)
                oracle.commit(wid, payload)
        assert ps.num_updates == oracle.num_updates
        got, want = ps.get_model(), oracle.get_model()
        np.testing.assert_allclose(got["w"], want["w"], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got["b"], want["b"], rtol=1e-5, atol=1e-6)
        for c in clients:
            c.close()
    finally:
        ps.stop()


def test_native_staleness_dynsgd_over_the_wire():
    """Wire mirror of test_ps_staleness_tracking_dynsgd: worker 0 pulls at
    version 0, two commits land before its commit → τ=2 → scale 1/3."""
    center = {"w": np.zeros(1, np.float32)}
    ps = make_server(center, DynSGDMerge(), 3)
    try:
        c0, c1, c2 = (make_client(ps, i) for i in range(3))
        c0.pull()
        c1.pull(); c1.commit(1, {"w": np.array([3.0], np.float32)})
        c2.pull(); c2.commit(2, {"w": np.array([4.0], np.float32)})
        c0.commit(0, {"w": np.array([3.0], np.float32)})
        np.testing.assert_allclose(ps.get_model()["w"], [3.0 + 4.0 + 1.0],
                                   rtol=1e-6)
        for c in (c0, c1, c2):
            c.close()
    finally:
        ps.stop()


def test_native_concurrent_hammer():
    """N threads pull/commit concurrently; every update lands exactly once
    (the C++ mutex serializes folds without the GIL serializing clients)."""
    center = {"w": np.zeros(2048, np.float32)}
    ps = make_server(center, ADAGMerge(), 4)
    try:
        def worker(i):
            c = make_client(ps, i)
            for _ in range(25):
                c.pull()
                c.commit(i, {"w": np.full(2048, 0.5, np.float32)})
            c.close()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert ps.num_updates == 100
        np.testing.assert_allclose(ps.get_model()["w"], 100 * 0.5 / 4,
                                   rtol=1e-4)
    finally:
        ps.stop()


def test_native_rejects_garbage_and_wrong_length():
    """A hostile/garbled connection is dropped at the handshake (no
    attacker-sized allocation is even possible — the frame size is pinned by
    the server's own vector length) and the server keeps serving."""
    from distkeras_tpu.native_ps import NativePSClient

    center = {"w": np.zeros(8, np.float32)}
    ps = make_server(center, DownpourMerge(), 1)
    try:
        # wrong magic
        s = socket.create_connection(("127.0.0.1", ps.port), timeout=5)
        s.sendall(b"EVIL!\n" + struct.pack("<IQ", 0, 8))
        try:
            assert s.recv(1) == b""  # dropped without an accept byte
        except ConnectionResetError:
            pass  # an RST is an equally valid "dropped"
        s.close()
        # right magic, wrong vector length → rejected in the handshake ack
        with pytest.raises(ConnectionError, match="vector length"):
            bad_spec = type("S", (), {"n": 9999})()
            NativePSClient("127.0.0.1", ps.port, 0, bad_spec)
        # the server is still alive and correct for a well-formed client
        c = make_client(ps, 0)
        c.commit(0, {"w": np.ones(8, np.float32)})
        np.testing.assert_allclose(ps.get_model()["w"], 1.0)
        c.close()
    finally:
        ps.stop()


def test_native_client_resolves_hostnames_and_bounds_roundtrips():
    """DNS names work (Python owns connection establishment — 'localhost',
    not just dotted quads) and set_timeout turns a wedged server into a
    ConnectionError instead of an eternal hang."""
    from distkeras_tpu.native_ps import NativePSClient

    center = {"w": np.zeros(4, np.float32)}
    ps = make_server(center, DownpourMerge(), 1)
    try:
        c = NativePSClient("localhost", ps.port, 0, ps.spec)
        c.commit(0, {"w": np.ones(4, np.float32)})
        np.testing.assert_allclose(ps.get_model()["w"], 1.0)
        c.close()
    finally:
        ps.stop()

    # a listener that accepts the handshake conversation never gets written:
    # connect to a silent socket and watch the bounded pull fail fast
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    try:
        silent_spec = type("S", (), {"n": 4})()
        with pytest.raises(ConnectionError, match="handshake"):
            # silent server: handshake ack never arrives — the connect-time
            # bound (connect_timeout also caps the handshake recv) fires
            NativePSClient("127.0.0.1", lst.getsockname()[1], 0,
                           silent_spec, connect_timeout=1.0)
    finally:
        lst.close()


def test_native_num_updates_setter_roundtrip():
    center = {"w": np.zeros(2, np.float32)}
    ps = make_server(center, DownpourMerge(), 1)
    try:
        ps.num_updates = 17  # the resume path in workers.py does exactly this
        assert ps.num_updates == 17
    finally:
        ps.stop()


def test_native_rejects_custom_merge_rules():
    from distkeras_tpu.native_ps import fold_mode
    from distkeras_tpu.parallel.merge_rules import MergeRule

    class Weird(MergeRule):
        def fold(self, center, commit, num_workers, staleness):
            return center

    with pytest.raises(ValueError, match="socket"):
        fold_mode(Weird(), 4)


def test_native_transport_trainer_end_to_end():
    """ADAG on backend='ps' with ps_transport='native' learns, exactly like
    the socket-transport test it mirrors."""
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=1024)
    t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="sgd", learning_rate=0.1, num_workers=2,
             batch_size=32, communication_window=2, num_epoch=2,
             backend="ps", ps_transport="native")
    t.train(ds, shuffle=True)
    assert final_loss(t) < 0.6


def test_native_vs_socket_transport_same_result():
    """Same trainer config, shuffle=False: the native transport's final
    params match the socket transport's (both lower to the same fold
    sequence when workers run the same deterministic schedule)."""
    from distkeras_tpu import DOWNPOUR

    def run(transport):
        ds = blobs_dataset(n=512)
        t = DOWNPOUR(model_spec(), loss="sparse_softmax_cross_entropy",
                     worker_optimizer="sgd", learning_rate=0.05,
                     num_workers=1, batch_size=32, communication_window=2,
                     num_epoch=1, backend="ps", ps_transport=transport)
        return t.train(ds)

    import jax

    a, b = run("socket"), run("native")
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=5e-5, atol=1e-6)


def test_native_int8_commit_wire_matches_codec_decode(rng):
    """Action 4 (segmented int8): the C++ fold must see exactly the tree
    Int8Codec.decode yields — per-leaf scales applied per segment — so
    worker-side error feedback matches what the center received."""
    from distkeras_tpu.parallel.compression import Int8Codec

    center = {"dense": {"kernel": np.zeros((16, 8), np.float32),
                        "bias": np.zeros(8, np.float32)},
              "gain": np.zeros(3, np.float32)}
    ps = make_server(center, DownpourMerge(), num_workers=1)
    try:
        c = make_client(ps, 0)
        codec = Int8Codec(min_size=1)
        delta = {"dense": {"kernel": rng.normal(size=(16, 8)).astype(np.float32),
                           "bias": rng.normal(size=8).astype(np.float32)},
                 "gain": rng.normal(size=3).astype(np.float32)}
        blob = codec.encode(delta)
        c.pull()
        c.commit(0, blob)           # rides the int8 wire
        got = ps.get_model()
        want = codec.decode(blob)   # DOWNPOUR fold: center += decoded
        import jax

        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)
        assert ps.num_updates == 1
        c.close()
    finally:
        ps.stop()


def test_native_int8_rejects_malformed_segments(rng):
    """Hostile/garbled segment headers (lengths not summing to the pinned
    n) drop the connection without folding or oversizing anything."""
    import ctypes

    from distkeras_tpu.native_ps import _f32p

    center = {"w": np.zeros(64, np.float32)}
    ps = make_server(center, DownpourMerge(), num_workers=1)
    try:
        c = make_client(ps, 0)
        qv = np.ones(64, np.int8)
        lens = np.asarray([100], np.uint64)  # != n: must be rejected
        scales = np.ones(1, np.float32)
        rc = c._lib.dkps_client_commit_int8(
            c._handle,
            qv.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            _f32p(scales), 1,
        )
        assert rc != 0                      # no ack: connection dropped
        assert ps.num_updates == 0
        np.testing.assert_array_equal(ps.get_model()["w"], 0.0)
        c.close()
    finally:
        ps.stop()


def test_native_transport_trains_with_int8_compression():
    """End-to-end: DOWNPOUR over the native transport with
    compression='int8' — commits ride the segmented wire (4x fewer
    payload bytes) and training still converges."""
    from distkeras_tpu import DOWNPOUR

    ds = blobs_dataset(n=2048)
    t = DOWNPOUR(model_spec(), loss="sparse_softmax_cross_entropy",
                 worker_optimizer="sgd", learning_rate=0.02, num_workers=4,
                 batch_size=32, communication_window=2, num_epoch=3,
                 backend="ps", ps_transport="native", compression="int8")
    t.train(ds, shuffle=True)
    assert final_loss(t) < 0.6, final_loss(t)


def test_native_stats_parity_with_python_ps():
    """stats() key parity: the C++ server exposes the identical counter
    set the Python PS does, and counts wire ops the same way (one pull,
    one compressed pull, one raw + one int8 commit here)."""
    from distkeras_tpu.native_ps import FlatSpec, NativePSClient
    from distkeras_tpu.parallel.compression import Int8Codec

    rng = np.random.default_rng(9)
    center = {"w": rng.normal(size=(40, 40)).astype(np.float32)}
    delta = {"w": rng.normal(size=(40, 40)).astype(np.float32)}
    ps = make_server(center, DownpourMerge(), 2)
    try:
        c0 = make_client(ps, 0)
        c1 = NativePSClient("127.0.0.1", ps.port, 1, FlatSpec(center),
                            pull_compression="int8")
        c0.pull()
        c0.commit(0, delta)
        c1.pull()
        c1.commit(1, Int8Codec(min_size=1).encode(delta))
        s = ps.stats()

        py = ParameterServer(center, DownpourMerge(), 2)
        py.pull(0)
        py.commit(0, delta)
        py.pull(1, compressed=True)
        py.commit(1, delta)
        ps_keys, py_keys = set(s), set(py.stats())
        assert ps_keys == py_keys, ps_keys ^ py_keys
        assert s["pulls"] == 1
        assert s["compressed_pulls"] == 1
        assert s["commits"] == 2
        # payload accounting: raw pull reply moves 40·40 f32, plus the
        # compressed pull's scales + int8 payload (protocol headers are
        # excluded on both transports)
        assert s["bytes_out"] >= 40 * 40 * 4 + 40 * 40
        assert s["bytes_in"] >= 40 * 40 * 4 + 40 * 40
        # 2 pull snapshots + 2 commit folds under the center mutex
        assert s["center_lock_acquires"] == 4
        assert s["center_lock_mean_hold_ns"] >= 0
        assert s["pulls_per_sec"] > 0 and s["commits_per_sec"] > 0
        c0.close()
        c1.close()
    finally:
        ps.stop()


def test_native_ema_matches_python_ps(rng):
    """The C++ per-commit EMA fold equals the Python PS's, commit for
    commit (same decay, same fold sequence)."""
    center = {"w": np.zeros(48, np.float32), "b": np.zeros(5, np.float32)}
    d = 0.7
    py = ParameterServer(center, DownpourMerge(), 1, ema_decay=d)
    ps = make_server(center, DownpourMerge(), 1, ema_decay=d)
    try:
        c = make_client(ps, 0)
        for i in range(4):
            delta = {"w": rng.normal(size=48).astype(np.float32),
                     "b": rng.normal(size=5).astype(np.float32)}
            py.pull(0); py.commit(0, delta)
            c.pull(); c.commit(0, delta)
        import jax

        for a, b in zip(jax.tree.leaves(ps.get_ema()),
                        jax.tree.leaves(py.get_ema())):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)
        c.close()
    finally:
        ps.stop()


def test_native_transport_trainer_ema_end_to_end():
    from distkeras_tpu import DOWNPOUR

    ds = blobs_dataset(n=1024)
    t = DOWNPOUR(model_spec(), loss="sparse_softmax_cross_entropy",
                 worker_optimizer="sgd", learning_rate=0.02, num_workers=2,
                 batch_size=32, communication_window=2, num_epoch=2,
                 backend="ps", ps_transport="native", ema_decay=0.9)
    t.train(ds, shuffle=True)
    assert t.ema_params_ is not None
    import jax

    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree.leaves(t.ema_params_))
