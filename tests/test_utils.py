import jax.numpy as jnp
import numpy as np

from distkeras_tpu import utils


def _tree():
    return {"a": jnp.arange(6.0).reshape(2, 3), "b": {"c": jnp.ones((4,))}}


def test_tree_math():
    t = _tree()
    s = utils.tree_add(t, t)
    assert np.allclose(s["a"], 2 * np.arange(6).reshape(2, 3))
    d = utils.tree_sub(s, t)
    assert np.allclose(d["b"]["c"], 1.0)
    z = utils.tree_zeros_like(t)
    assert np.allclose(z["a"], 0)
    sc = utils.tree_scale(t, 3.0)
    assert np.allclose(sc["b"]["c"], 3.0)
    n = utils.tree_to_numpy(t)
    assert isinstance(n["a"], np.ndarray)


def test_tree_stack_unstack():
    t = _tree()
    stacked = utils.tree_stack([t, utils.tree_scale(t, 2.0)])
    assert stacked["a"].shape == (2, 2, 3)
    back = utils.tree_unstack(stacked, 2)
    assert np.allclose(back[1]["b"]["c"], 2.0)
    b = utils.tree_broadcast_to_workers(t, 5)
    assert b["a"].shape == (5, 2, 3)
    assert np.allclose(b["a"][3], t["a"])


def test_weights_serde_roundtrip():
    t = {"w": np.random.default_rng(0).normal(size=(3, 5)).astype(np.float32),
         "nested": {"b": np.arange(7, dtype=np.int32)}}
    blob = utils.serialize_weights(t)
    assert isinstance(blob, bytes)
    back = utils.deserialize_weights(blob)
    assert np.array_equal(back["w"], t["w"])
    assert np.array_equal(back["nested"]["b"], t["nested"]["b"])
    assert back["nested"]["b"].dtype == np.int32


def test_uniform_weights():
    t = {"w": jnp.zeros((100, 10)), "b": jnp.zeros((10,), jnp.float32)}
    u = utils.uniform_weights(t, bounds=(-0.25, 0.25), seed=1)
    w = np.asarray(u["w"])
    assert w.min() >= -0.25 and w.max() <= 0.25
    assert w.std() > 0.05  # actually randomized


def test_count_params():
    t = _tree()
    assert utils.tree_count_params(t) == 10


def test_enable_compilation_cache(tmp_path, monkeypatch):
    """The helper points JAX's persistent cache where asked (explicit arg >
    JAX_COMPILATION_CACHE_DIR env > tmp default) and the config keys exist
    in this JAX version."""
    import jax

    from distkeras_tpu.utils import enable_compilation_cache

    before = jax.config.jax_compilation_cache_dir
    try:
        got = enable_compilation_cache(str(tmp_path / "explicit"))
        assert got == str(tmp_path / "explicit")
        assert jax.config.jax_compilation_cache_dir == got

        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR",
                           str(tmp_path / "from_env"))
        assert enable_compilation_cache() == str(tmp_path / "from_env")
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 1.0
    finally:
        # restore the conftest-configured cache for the rest of the suite
        enable_compilation_cache(before)
