"""Merge rules vs a NumPy oracle — the reference PS commit semantics
(SURVEY.md §2b.3) as ground truth for both backends."""

import numpy as np

from distkeras_tpu import utils
from distkeras_tpu.parallel import merge_rules as mr


def setup_trees(W=4, seed=0):
    rng = np.random.default_rng(seed)
    center = {"w": rng.normal(size=(3, 2)).astype(np.float32),
              "b": rng.normal(size=(2,)).astype(np.float32)}
    workers = {
        "w": np.stack([center["w"] + rng.normal(size=(3, 2)).astype(np.float32)
                       for _ in range(W)]),
        "b": np.stack([center["b"] + rng.normal(size=(2,)).astype(np.float32)
                       for _ in range(W)]),
    }
    return center, workers


def deltas(center, workers):
    return {k: workers[k] - center[k][None] for k in center}


def test_adag_is_mean_of_deltas():
    center, workers = setup_trees()
    d = deltas(center, workers)
    new_center, new_workers = mr.ADAGMerge().merge(center, workers)
    for k in center:
        assert np.allclose(new_center[k], center[k] + d[k].mean(0), atol=1e-6)
        # workers re-based onto the new center
        assert np.allclose(new_workers[k], np.broadcast_to(
            np.asarray(new_center[k])[None], workers[k].shape), atol=1e-6)


def test_downpour_is_sum_of_deltas():
    center, workers = setup_trees()
    d = deltas(center, workers)
    new_center, _ = mr.DownpourMerge().merge(center, workers)
    for k in center:
        assert np.allclose(new_center[k], center[k] + d[k].sum(0), atol=1e-5)


def test_elastic_average_moves_both_sides():
    center, workers = setup_trees()
    alpha = 0.05
    rule = mr.ElasticAverageMerge(alpha)
    d = deltas(center, workers)
    new_center, new_workers = rule.merge(center, workers)
    for k in center:
        diff = alpha * d[k]
        assert np.allclose(new_center[k], center[k] + diff.sum(0), atol=1e-5)
        assert np.allclose(new_workers[k], workers[k] - diff, atol=1e-6)
    assert rule.resets_workers is False


def test_dynsgd_fold_position_staleness():
    center, workers = setup_trees()
    d = deltas(center, workers)
    new_center, _ = mr.DynSGDMerge().merge(center, workers)
    W = workers["w"].shape[0]
    for k in center:
        scale = (1.0 / (np.arange(W) + 1.0)).reshape((W,) + (1,) * center[k].ndim)
        expected = center[k] + (d[k] * scale).sum(0)
        assert np.allclose(new_center[k], expected, atol=1e-5)


def test_async_fold_matches_semantics():
    center, workers = setup_trees(W=2)
    d = deltas(center, workers)
    one = {k: d[k][0] for k in d}
    c_down = mr.DownpourMerge().fold(center, one, num_workers=2, staleness=0)
    c_adag = mr.ADAGMerge().fold(center, one, num_workers=2, staleness=0)
    c_dyn = mr.DynSGDMerge().fold(center, one, num_workers=2, staleness=3)
    for k in center:
        assert np.allclose(c_down[k], center[k] + one[k], atol=1e-6)
        assert np.allclose(c_adag[k], center[k] + one[k] / 2, atol=1e-6)
        assert np.allclose(c_dyn[k], center[k] + one[k] / 4, atol=1e-6)


def test_adag_window1_equals_sync_sgd_allreduce():
    """ADAG with window=1 must equal plain synchronous mean-gradient SGD."""
    rng = np.random.default_rng(1)
    center = {"w": rng.normal(size=(4,)).astype(np.float32)}
    lr = 0.1
    grads = rng.normal(size=(3, 4)).astype(np.float32)  # per-worker grads
    # each worker does one SGD step from the center
    workers = {"w": np.stack([center["w"] - lr * g for g in grads])}
    new_center, _ = mr.ADAGMerge().merge(center, workers)
    expected = center["w"] - lr * grads.mean(0)
    assert np.allclose(new_center["w"], expected, atol=1e-6)


def test_get_merge_rule():
    assert isinstance(mr.get_merge_rule("adag"), mr.ADAGMerge)
    assert isinstance(mr.get_merge_rule("downpour"), mr.DownpourMerge)
    r = mr.get_merge_rule("aeasgd", rho=2.0, learning_rate=0.1)
    assert isinstance(r, mr.ElasticAverageMerge) and np.isclose(r.alpha, 0.2)
    assert isinstance(mr.get_merge_rule("dynsgd"), mr.DynSGDMerge)
