"""ISSUE 13: the watchtower — timeseries store, scraper, watchdog, guard.

Pins, per the acceptance criteria:

- bounded ring series with downsampling (gauges average, counters stay
  monotone), whole-run coverage, trailing-window rate/delta/increase
  reads (increase is reset-aware — a failed-over PS restarting its
  counters must not mask a replay spike);
- every watchdog rule fires deterministically on hand-built series and
  stays silent on healthy ones; transitions (fire AND resolve) land in
  the ledger and the hook;
- THE shared definition: ``ElasticPolicy``'s rounds/s + straggler
  observations come from the same :func:`rates_from_counts` /
  :func:`straggler_workers` / ``worker.<wid>.windows`` series the
  commit-skew rule evaluates — ``observe`` and ``observe_series``
  agree decision-for-decision on the same data;
- the chaos acceptance: a seeded socket run with an injected straggler
  + a PS kill produces a timeseries dump and >= 3 distinct alert types;
  the SAME run with no faults produces zero alerts;
- satellites: ``trace_dropped_spans`` surfaced (registry + health
  snapshot), the shm segment inventory in ``health_snapshot``, and the
  ``health --watch`` CLI path over a live server's ``metrics`` action.
"""

import json
import os
import threading
import warnings

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.observability import trace
from distkeras_tpu.observability.metrics import (
    health_snapshot,
    ps_metrics,
    trace_metrics,
    wire_series_samples,
)
from distkeras_tpu.observability.timeseries import (
    Scraper,
    Series,
    TimeSeriesStore,
    history_source,
    progress_source,
    ps_source,
    serving_source,
)
from distkeras_tpu.observability.watch import (
    CommitReplaySpikeRule,
    CommitSkewRule,
    LossStallRule,
    RingOccupancyRule,
    ServingSLORule,
    SLOClass,
    TauP95Rule,
    WalFsyncTailRule,
    Watchdog,
    Watchtower,
    rates_from_counts,
    straggler_workers,
    watch_endpoint,
    worker_rates,
)
from distkeras_tpu.parallel.merge_rules import DownpourMerge
from distkeras_tpu.parameter_servers import (
    ParameterServer,
    SocketParameterServer,
    build_ps_stats,
)
from tests.test_trainers import blobs_dataset, model_spec


@pytest.fixture(autouse=True)
def _trace_off():
    trace.disable()
    yield
    trace.disable()


# -- Series / TimeSeriesStore -------------------------------------------------


def test_series_gauge_downsamples_and_keeps_whole_span():
    s = Series("g", "gauge", capacity=16)
    for i in range(100):
        s.append(float(i), float(i))
    pts = s.points()
    assert len(pts) < 16
    # whole-run coverage: first point near the start, last IS the last
    assert pts[0][0] < 20
    assert pts[-1] == (99.0, 99.0)
    assert s.resolution > 1
    # gauge merge averages: values stay within the sampled range
    assert all(0.0 <= v <= 99.0 for _, v in pts)


def test_series_counter_downsample_stays_monotone():
    s = Series("c", "counter", capacity=16)
    for i in range(200):
        s.append(float(i), float(i * 3))
    vals = [v for _, v in s.points()]
    assert vals == sorted(vals)          # never invents a decrease
    assert vals[-1] == 3 * 199
    assert s.rate(1000.0) == pytest.approx(3.0)


def test_series_window_and_rate():
    s = Series("c", "counter", capacity=64)
    for i in range(10):
        s.append(float(i), float(i * 2))
    assert len(s.window(7.0)) == 3        # t = 7, 8, 9
    assert s.rate(4.0) == pytest.approx(2.0)
    assert s.rate(0.5) is None            # one in-window point


def test_store_kind_conflict_and_json_roundtrip(tmp_path):
    st = TimeSeriesStore()
    st.sample("a", 0.0, 1.0, "counter")
    with pytest.raises(ValueError, match="is a counter"):
        st.sample("a", 1.0, 2.0, "gauge")
    st.sample("b", 0.0, 5.0)
    path = st.dump(str(tmp_path / "ts.json"), extra={"alerts": {"log": []}})
    doc = json.loads(open(path).read())
    assert set(doc["series"]) == {"a", "b"}
    assert doc["alerts"] == {"log": []}
    st2 = TimeSeriesStore.load(path)
    assert st2.get("a").points() == st.get("a").points()
    assert st2.get("a").kind == "counter"


def test_store_increase_is_reset_aware():
    st = TimeSeriesStore()
    for t, v in [(0, 0), (1, 5), (2, 8), (3, 1), (4, 4)]:  # reset at t=3
        st.sample("c", float(t), float(v), "counter")
    assert st.delta("c", 10.0) == pytest.approx(4.0)       # last - first
    assert st.increase("c", 10.0) == pytest.approx(11.0)   # 5+3+0+3


# -- Scraper ------------------------------------------------------------------


def test_scraper_tick_sources_and_failure_isolation():
    st = TimeSeriesStore()
    sc = Scraper(st, interval=10.0)
    calls = {"n": 0}

    def good(store, now):
        calls["n"] += 1
        store.sample("ok", now, calls["n"], "counter")

    def bad(store, now):
        raise RuntimeError("boom")

    sc.add_source("bad", bad)
    sc.add_source("good", good)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        sc.tick(1.0)
        sc.tick(2.0)
    # the bad source is disabled after ONE warning; good keeps sampling
    assert sum("bad" in str(x.message) for x in w) == 1
    assert calls["n"] == 2
    assert st.last("ok") == 2.0


def test_progress_and_history_sources():
    st = TimeSeriesStore()
    progress = {0: 4, 1: 7}
    progress_source(lambda: progress)(st, 1.0)
    assert st.last("worker.0.windows") == 4.0
    assert st.last("worker.1.windows") == 7.0
    hist = [{"loss": 1.0}, {"loss": 3.0}, {"no_loss": True}]
    history_source(hist, threading.Lock(), tail=2)(st, 1.0)
    assert st.last("train.records") == 3.0
    assert st.last("train.loss") == pytest.approx(3.0)  # last-2 mean, one NaN-free


def test_ps_source_samples_stats_tau_and_wal(tmp_path):
    ps = ParameterServer({"w": np.zeros(8, np.float32)}, DownpourMerge(),
                         2, wal_dir=str(tmp_path / "wal"),
                         snapshot_every=1000, wal_group_window=1)
    ps.pull(0)
    for k in range(5):
        ps.commit(0, {"w": np.ones(8, np.float32)}, seq=k + 1)
    ps._wal.sync()
    st = TimeSeriesStore()
    ps_source(ps)(st, 1.0)
    assert st.last("ps.commits") == 5.0
    assert st.last("ps.tau_p95") is not None
    assert st.last("ps.wal_fsync_p95_ms") is not None
    ps._close_durability()


# -- the shared rounds/s + straggler definitions ------------------------------


def test_rates_and_straggler_definitions():
    rates = rates_from_counts(0.0, {0: 0, 1: 0}, 2.0, {0: 8, 1: 2, 2: 4})
    assert rates == {0: 4.0, 1: 1.0, 2: 2.0}
    med, lag = straggler_workers({0: 10.0, 1: 0.5, 2: 9.0}, 0.25)
    assert med == 9.0 and lag == [1]
    assert straggler_workers({0: 1.0}, 0.25) == (0.0, [])
    # worker_rates reads the same series the coordinator writes; a
    # single-point worker (just joined) has no rate yet
    st = TimeSeriesStore()
    _feed = [(0.0, 0), (2.0, 8)]
    for t, v in _feed:
        st.sample("worker.0.windows", t, v, "counter")
    st.sample("worker.9.windows", 2.0, 1, "counter")
    assert worker_rates(st, 10.0, 2.0) == {0: 4.0}


def test_elastic_policy_observe_and_observe_series_agree():
    """The single-definition acceptance: fed the same progression, the
    legacy counts path and the shared-timeseries path make the same
    decisions (join under target; straggler release)."""
    from distkeras_tpu.resilience.elastic import ElasticPolicy

    steps = [
        (0.0, {0: 0, 1: 0, 2: 0}),
        (1.0, {0: 2, 1: 2, 2: 2}),    # total 6/s < 0.85*10 -> join
        (2.0, {0: 14, 1: 10, 2: 2}),  # 2 stalls -> straggler release
    ]
    p1 = ElasticPolicy(target_rounds_per_sec=10.0, max_workers=4,
                       cooldown_s=0.0, patience=1)
    got1 = [p1.observe(t, c) for t, c in steps]

    p2 = ElasticPolicy(target_rounds_per_sec=10.0, max_workers=4,
                       cooldown_s=0.0, patience=1, window_s=1.5)
    store = TimeSeriesStore()
    got2 = []
    for t, counts in steps:
        for wid, n in counts.items():
            store.sample(f"worker.{wid}.windows", t, n, "counter")
        got2.append(p2.observe_series(store, t, wids=counts.keys()))
    assert got1 == [[], [("join", None)], [("release", 2)]]
    assert got2 == got1


# -- watchdog rules, deterministically ----------------------------------------


def _feed(store, name, pts, kind="gauge"):
    for t, v in pts:
        store.sample(name, float(t), float(v), kind)


def test_tau_rule_fires_and_resolves():
    st = TimeSeriesStore()
    dog = Watchdog(st, rules=[TauP95Rule(bound=8.0)])
    assert dog.evaluate(0.0) == []               # no data: no transition
    st.sample("ps.tau_p95", 1.0, 3.0)
    assert dog.evaluate(1.0) == []
    st.sample("ps.tau_p95", 2.0, 20.0)
    (fired,) = dog.evaluate(2.0)
    assert fired["kind"] == "tau_p95" and fired.firing
    assert fired["value"] == 20.0 and fired["threshold"] == 8.0
    st.sample("ps.tau_p95", 3.0, 2.0)
    (resolved,) = dog.evaluate(3.0)
    assert resolved["state"] == "resolved"
    assert dog.counts() == {"tau_p95": 1}
    assert not dog.active


def test_commit_skew_rule_straggler_vs_balanced():
    st = TimeSeriesStore()
    rule = CommitSkewRule(ratio=0.25, window_s=5.0, min_rounds=4,
                          persistence=1)
    _feed(st, "worker.0.windows", [(0, 0), (5, 50)], "counter")
    _feed(st, "worker.1.windows", [(0, 0), (5, 1)], "counter")
    firing, worst, detail = rule.evaluate(st, 5.0)
    assert firing and detail["stragglers"] == {"1": 0.2}
    st2 = TimeSeriesStore()
    _feed(st2, "worker.0.windows", [(0, 0), (5, 50)], "counter")
    _feed(st2, "worker.1.windows", [(0, 0), (5, 45)], "counter")
    rule2 = CommitSkewRule(ratio=0.25, window_s=5.0, min_rounds=4,
                           persistence=1)
    firing2, _, _ = rule2.evaluate(st2, 5.0)
    assert firing2 is False
    # persistence: one noisy window does not page
    rule3 = CommitSkewRule(ratio=0.25, window_s=5.0, min_rounds=4,
                           persistence=2)
    assert rule3.evaluate(st, 5.0)[0] is False
    assert rule3.evaluate(st, 5.0)[0] is True


def test_commit_skew_rule_warmup_grace():
    """A worker whose series does not yet span a full rate window is
    still warming up (startup GIL scramble, an elastic joiner's first
    moments) — not judged; once the window fills, it is."""
    st = TimeSeriesStore()
    _feed(st, "worker.0.windows", [(0, 0), (1, 10), (5, 50)], "counter")
    _feed(st, "worker.1.windows", [(4, 1), (5, 1)], "counter")  # young
    rule = CommitSkewRule(ratio=0.25, window_s=5.0, min_rounds=4,
                          persistence=1)
    # pool of ONE judgeable worker: no verdict at all
    assert rule.evaluate(st, 5.0)[0] is None
    # the young worker's window fills — and it genuinely stalled
    _feed(st, "worker.0.windows", [(9, 90)], "counter")
    _feed(st, "worker.1.windows", [(9, 1)], "counter")
    firing, _, detail = rule.evaluate(st, 9.0)
    assert firing is True and "1" in detail["stragglers"]


def test_replay_spike_rule_counts_dups_and_fenced_across_reset():
    st = TimeSeriesStore()
    rule = CommitReplaySpikeRule(max_in_window=3.0, window_s=10.0)
    assert rule.evaluate(st, 0.0)[0] is None
    _feed(st, "ps.dup_commits", [(0, 0), (1, 1)], "counter")
    _feed(st, "ps.fenced_commits", [(0, 0), (1, 1)], "counter")
    assert rule.evaluate(st, 1.0)[0] is False    # 2 <= 3
    # failover reset mid-window: 1 -> 0 -> 3 is an increase of 4, not 2
    _feed(st, "ps.dup_commits", [(2, 0), (3, 3)], "counter")
    firing, value, detail = rule.evaluate(st, 3.0)
    assert firing and value == pytest.approx(5.0)
    assert detail["dup_commits"] == pytest.approx(4.0)


def test_wal_and_ring_rules():
    st = TimeSeriesStore()
    wal = WalFsyncTailRule(p95_ms=50.0)
    ring = RingOccupancyRule(frac=0.9)
    assert wal.evaluate(st, 0.0)[0] is None
    assert ring.evaluate(st, 0.0)[0] is None
    st.sample("ps.wal_fsync_p95_ms", 1.0, 80.0)
    st.sample("shm.ring_occupancy_frac", 1.0, 0.95)
    assert wal.evaluate(st, 1.0)[0] is True
    assert ring.evaluate(st, 1.0)[0] is True
    st.sample("ps.wal_fsync_p95_ms", 2.0, 5.0)
    st.sample("shm.ring_occupancy_frac", 2.0, 0.1)
    assert wal.evaluate(st, 2.0)[0] is False
    assert ring.evaluate(st, 2.0)[0] is False


def test_serving_slo_rule_per_class_with_breakdown():
    st = TimeSeriesStore()
    rule = ServingSLORule(slo={
        "interactive": SLOClass(p50_ms=50.0, p99_ms=200.0),
        "batch": SLOClass(p99_ms=5000.0),
    })
    assert rule.evaluate(st, 0.0)[0] is None     # no latency data yet
    st.sample("serve.lat.interactive.p50_ms", 1.0, 20.0)
    st.sample("serve.lat.interactive.p99_ms", 1.0, 150.0)
    st.sample("serve.lat.batch.p99_ms", 1.0, 900.0)
    assert rule.evaluate(st, 1.0)[0] is False
    st.sample("serve.lat.interactive.p99_ms", 2.0, 450.0)
    st.sample("serve.lat.interactive.queue_ms", 2.0, 300.0)
    firing, worst, detail = rule.evaluate(st, 2.0)
    assert firing and worst == pytest.approx(450.0 / 200.0)
    miss = detail["misses"]["interactive"]
    assert miss["missed"] == "p99_ms" and miss["queue_ms"] == 300.0
    assert "batch" not in detail["misses"]


def test_loss_stall_rule_needs_progress_and_flat_slope():
    st = TimeSeriesStore()
    rule = LossStallRule(window_s=8.0, min_points=4, min_new_records=4,
                         slope_eps=1e-4, persistence=1)
    # converging: silent
    _feed(st, "train.loss", [(t, 2.0 - 0.1 * t) for t in range(8)])
    _feed(st, "train.records", [(t, 10 * t) for t in range(8)], "counter")
    assert rule.evaluate(st, 7.0)[0] is False
    # flat loss WITH progress: stall
    st2 = TimeSeriesStore()
    _feed(st2, "train.loss", [(t, 1.5) for t in range(8)])
    _feed(st2, "train.records", [(t, 10 * t) for t in range(8)], "counter")
    rule2 = LossStallRule(window_s=8.0, min_points=4,
                          min_new_records=4, slope_eps=1e-4,
                          persistence=1)
    assert rule2.evaluate(st2, 7.0)[0] is True
    # flat loss WITHOUT progress (run finished/idle): silent
    st3 = TimeSeriesStore()
    _feed(st3, "train.loss", [(t, 1.5) for t in range(8)])
    _feed(st3, "train.records", [(t, 80) for t in range(8)], "counter")
    rule3 = LossStallRule(window_s=8.0, min_points=4,
                          min_new_records=4, slope_eps=1e-4,
                          persistence=1)
    assert rule3.evaluate(st3, 7.0)[0] is None
    # span gate: enough points but covering a sliver of the window
    # (startup — loss wobbling out of init noise) is never judged
    st4 = TimeSeriesStore()
    _feed(st4, "train.loss", [(t / 10.0, 1.5) for t in range(8)])
    _feed(st4, "train.records",
          [(t / 10.0, 10 * t) for t in range(8)], "counter")
    rule4 = LossStallRule(window_s=8.0, min_points=4,
                          min_new_records=4, slope_eps=1e-4,
                          persistence=1)
    assert rule4.evaluate(st4, 0.7)[0] is None


def test_watchdog_hook_and_duplicate_rule_names():
    st = TimeSeriesStore()
    seen = []
    dog = Watchdog(st, rules=[TauP95Rule(bound=1.0)],
                   hooks=[seen.append])
    st.sample("ps.tau_p95", 0.0, 5.0)
    dog.evaluate(0.0)
    assert len(seen) == 1 and seen[0]["kind"] == "tau_p95"
    with pytest.raises(ValueError, match="duplicate rule names"):
        Watchdog(st, rules=[TauP95Rule(), TauP95Rule()])


def test_watchtower_bundle_dump(tmp_path):
    wt = Watchtower(rules=[TauP95Rule(bound=4.0)], interval=10.0)
    wt.add_source("fake", lambda store, now:
                  store.sample("ps.tau_p95", now, 9.0))
    wt.tick(1.0)
    assert [a["kind"] for a in wt.alerts] == ["tau_p95"]
    path = wt.dump(str(tmp_path / "watch.json"))
    doc = json.loads(open(path).read())
    assert "ps.tau_p95" in doc["series"]
    assert doc["alerts"]["counts"] == {"tau_p95": 1}
    assert doc["alerts"]["active"] == ["tau_p95"]


# -- serving latency summary --------------------------------------------------


def test_summarize_latencies_and_serving_source():
    from distkeras_tpu.serving.scheduler import summarize_latencies

    recs = [
        {"t": float(i), "slo_class": "default", "state": "done",
         "total_s": 0.1 * (i + 1), "queue_s": 0.01, "prefill_s": 0.02,
         "decode_s": 0.05, "new_tokens": 4}
        for i in range(10)
    ]
    recs.append({"t": 3.0, "slo_class": "batch", "state": "done",
                 "total_s": 2.0, "queue_s": None, "prefill_s": None,
                 "decode_s": None, "new_tokens": 1})
    lat = summarize_latencies(recs)
    assert set(lat) == {"default", "batch"}
    assert lat["default"]["count"] == 10
    assert lat["default"]["p50_ms"] == pytest.approx(550.0, rel=0.1)
    assert lat["default"]["queue_ms"] == pytest.approx(10.0)
    assert lat["batch"]["p99_ms"] == pytest.approx(2000.0)
    # windowed: only the tail
    lat_w = summarize_latencies(recs, window_s=2.5, now=9.0)
    assert lat_w["default"]["count"] == 3

    class FakeEngine:
        def stats(self):
            return {"submitted": 11, "queued": 1, "latency": lat}

    st = TimeSeriesStore()
    serving_source(FakeEngine())(st, 1.0)
    assert st.last("serve.submitted") == 11.0
    assert st.last("serve.lat.default.p99_ms") == lat["default"]["p99_ms"]
    assert st.last("serve.lat.batch.p50_ms") == lat["batch"]["p50_ms"]


# -- satellites: trace overflow + shm inventory -------------------------------


def test_trace_dropped_spans_surfaced():
    trace.enable(ring_size=16)
    for i in range(50):
        with trace.span(f"s{i}"):
            pass
    # >= not ==: live daemon threads from earlier suite activity (WAL
    # flushers etc.) may record their own spans into this recorder —
    # THIS thread alone overflowed by exactly 34
    dropped = trace.dropped_spans()
    assert dropped >= 50 - 16
    reg = trace_metrics()
    doc = reg.to_json()
    assert doc["dk_trace_dropped_spans_total"]["samples"][0]["value"] \
        >= 50 - 16
    snap = health_snapshot()
    assert snap["trace"]["enabled"] is True
    assert snap["trace"]["dropped_spans"] >= 50 - 16
    trace.disable()
    # the counter survives the recorder (process-lifetime monotone)
    assert trace.dropped_spans() >= dropped


def test_health_snapshot_shm_inventory_and_alerts(tmp_path):
    from distkeras_tpu import shm

    seg = shm.mint_segment("dkshm_test", 4096)
    try:
        snap = health_snapshot()
        names = [s["name"] for s in snap["shm"]["segments"]]
        assert seg.name in names
        assert snap["shm"]["total_bytes"] >= seg.size
    finally:
        seg.close()
        seg.unlink()
        shm.unregister_segment(seg.name)
    snap2 = health_snapshot()
    assert seg.name not in [s["name"] for s in snap2["shm"]["segments"]]
    # an ACTIVE alert fails the one health document
    wt = Watchtower(rules=[TauP95Rule(bound=1.0)], interval=10.0)
    wt.add_source("fake", lambda store, now:
                  store.sample("ps.tau_p95", now, 5.0))
    wt.tick(0.0)
    snap3 = health_snapshot(watchtower=wt)
    assert snap3["ok"] is False
    assert snap3["alerts"]["active"] == ["tau_p95"]


# -- the wire: metrics action + health --watch --------------------------------


def test_wire_series_samples_inverse_mapping():
    stats = build_ps_stats(5, 0, 7, 100, 200, 9, 10, 11, 2.0,
                           dup_commits=3)
    reg = ps_metrics(stats)
    samples = dict(
        (name, (kind, value))
        for name, kind, value in wire_series_samples(reg.to_json())
    )
    assert samples["ps.commits"] == ("counter", 7)
    assert samples["ps.dup_commits"] == ("counter", 3)
    assert samples["ps.pool_size"] == ("gauge", 0)


def test_watch_endpoint_over_live_server_and_cli(capsys):
    center = {"w": np.zeros(32, np.float32)}
    ps = SocketParameterServer(center, DownpourMerge(), 1)
    ps.initialize()
    ps.start()
    # attach a watchtower so the wire reply carries a server-side ledger
    wt = Watchtower(rules=[TauP95Rule(bound=1.0)], interval=10.0)
    wt.add_source("fake", lambda store, now:
                  store.sample("ps.tau_p95", now, 7.0))
    wt.tick(0.0)
    ps.watchtower = wt
    try:
        from distkeras_tpu.observability.__main__ import _scrape, main

        reply = _scrape("127.0.0.1", ps.port)
        assert reply["alerts"]["active"] == ["tau_p95"]
        assert "dk_trace_dropped_spans_total" in reply["metrics"]

        emitted = []
        dog = watch_endpoint(
            lambda: _scrape("127.0.0.1", ps.port),
            rules=[CommitReplaySpikeRule(max_in_window=0.0,
                                         window_s=60.0)],
            interval=0.01, count=3, emit=emitted.append,
            sleep=lambda s: None,
        )
        # the server-side ledger is relayed exactly once, flagged remote
        remote = [e for e in emitted if e.get("remote")]
        assert len(remote) == 1 and remote[0]["kind"] == "tau_p95"
        assert not dog.active   # no dups on this server: local rules quiet
        assert dog.remote_active == ["tau_p95"]

        # the CLI front door: the exit code reflects a firing alert
        # wherever it lives — here only in the SERVER-side ledger
        rc = main(["health", "--host", "127.0.0.1",
                   "--port", str(ps.port), "--watch", "--count", "2",
                   "--interval", "0.01"])
        assert rc == 1
        out = capsys.readouterr().out
        for line in out.strip().splitlines():
            json.loads(line)    # transitions are JSON lines

        # with the server-side alert resolved, the CLI exits clean
        wt.watchdog.active.clear()
        rc2 = main(["health", "--host", "127.0.0.1",
                    "--port", str(ps.port), "--watch", "--count", "2",
                    "--interval", "0.01"])
        assert rc2 == 0
        capsys.readouterr()
    finally:
        ps.stop()


# -- trainer knob validation --------------------------------------------------


def test_trainer_watch_knob_validation():
    spec = model_spec()
    with pytest.raises(ValueError, match="backend='ps' only"):
        dk.ADAG(spec, loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", num_workers=1, batch_size=8,
                num_epoch=1, backend="collective", watch=True)
    with pytest.raises(ValueError, match="scrape_interval"):
        dk.ADAG(spec, loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", num_workers=1, batch_size=8,
                num_epoch=1, backend="ps", watch=True,
                scrape_interval=0.0)
    with pytest.raises(ValueError, match="watch_hook"):
        dk.ADAG(spec, loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", num_workers=1, batch_size=8,
                num_epoch=1, backend="ps", watch=True,
                watch_hook="not-callable")


# -- the chaos acceptance -----------------------------------------------------


def _watch_trainer(plan, tmp_path, rules, workers=4, epochs=3,
                   **extra):
    from distkeras_tpu.resilience.retry import RetryPolicy

    return dk.ADAG(
        model_spec(), loss="sparse_softmax_cross_entropy",
        worker_optimizer="sgd", learning_rate=0.05,
        num_workers=workers, batch_size=16, communication_window=2,
        num_epoch=epochs, backend="ps", ps_transport="socket",
        retry_policy=RetryPolicy(max_attempts=100, base_delay=0.005,
                                 max_delay=0.2, deadline=120),
        heartbeat_interval=0.05, fault_plan=plan,
        watch=True, watch_rules=rules, scrape_interval=0.05,
        watch_dir=str(tmp_path / "watch"), **extra,
    )


def _acceptance_rules():
    # thresholds jitter-hardened to the known ±15% suite-load envelope
    # (ISSUE 14 satellite): the clean run's τ p95 has been observed up
    # to ~8 under full-suite GIL scramble (bound raised 8→12 keeps the
    # straggler's τ≈30+ firing with big headroom while the clean run
    # stays quiet), and the skew ratio 0.3→0.35 keeps the straggler
    # below threshold even when suite load halves the healthy median
    # (a clean run's slowest/median stays ≥ ~0.7, 2× above 0.35)
    return [
        TauP95Rule(bound=12.0),
        CommitSkewRule(ratio=0.35, window_s=3.0, min_rounds=4,
                       persistence=1),
        CommitReplaySpikeRule(max_in_window=0.5, window_s=6.0),
        WalFsyncTailRule(p95_ms=10_000.0),
        LossStallRule(),
    ]


@pytest.mark.filterwarnings("ignore")
def test_watch_chaos_acceptance_straggler_plus_ps_kill(tmp_path):
    """The acceptance run: seeded straggler (worker 1 sleeps every
    window) + recv drops + a PS kill with WAL restart-in-place → the
    run completes AND the watchtower produces a timeseries dump with
    >= 3 distinct alert types (skew from the straggler, a dup/fenced
    replay spike from the drops + kill replays, a τ tail from the
    straggler's stale pulls)."""
    from distkeras_tpu.resilience.faults import FaultPlan

    ds = blobs_dataset(n=768)
    plan = FaultPlan(seed=7, drop_recv=0.06, max_faults=40,
                     straggle={1: 0.3}, kill_ps_after_commits=10)
    hook_kinds = []
    t = _watch_trainer(plan, tmp_path, _acceptance_rules(),
                       ps_wal_dir=str(tmp_path / "wal"),
                       ps_snapshot_every=5, ps_failover_timeout=0.4,
                       watch_hook=lambda a: hook_kinds.append(a["kind"]))
    with plan:
        t.train(ds, shuffle=True)
    assert plan.stats()["ps_kills"] == 1
    assert plan.stats()["straggles"] > 0

    ledger = t.watch_alerts_
    kinds = set(ledger["counts"])
    # >= 3 distinct alert types, including the two the faults target
    assert "commit_skew" in kinds, ledger
    assert "commit_replay_spike" in kinds, ledger
    assert len(kinds) >= 3, ledger
    # the hook saw every fire transition
    assert set(hook_kinds) >= kinds
    # the timeseries dump exists and carries the series + the ledger
    assert t.watch_path_ and os.path.exists(t.watch_path_)
    doc = json.loads(open(t.watch_path_).read())
    assert "ps.commits" in doc["series"]
    assert any(n.startswith("worker.") for n in doc["series"])
    assert doc["alerts"]["counts"] == ledger["counts"]
    # fire points are timestamped and ordered (deterministic replayable
    # evidence, not just a boolean)
    ts = [a["t"] for a in ledger["log"]]
    assert ts == sorted(ts) and len(ts) >= 3


@pytest.mark.filterwarnings("ignore")
def test_watch_clean_run_zero_alerts(tmp_path):
    """The same trainer/rule configuration with NO faults: zero alerts
    (the rules are judgments about failure shapes, not about load)."""
    ds = blobs_dataset(n=768)
    t = _watch_trainer(None, tmp_path, _acceptance_rules())
    t.train(ds, shuffle=True)
    assert t.watch_alerts_["log"] == [], t.watch_alerts_
    assert t.watch_alerts_["counts"] == {}
    # the dump still exists (telemetry is not only for bad days)
    assert t.watch_path_ and os.path.exists(t.watch_path_)


@pytest.mark.filterwarnings("ignore")
def test_elastic_autoscaler_reads_shared_store(tmp_path):
    """ElasticCoordinator feeds the SAME store the watchtower scrapes:
    worker.* series exist in the dump of an elastic watched run, and
    the policy's decisions came off them (observe_series path)."""
    ds = blobs_dataset(n=512)
    from distkeras_tpu.resilience.elastic import ElasticPolicy

    policy = ElasticPolicy(target_rounds_per_sec=1e-3, min_workers=1,
                           cooldown_s=60.0, window_s=1.0)
    t = dk.ADAG(
        model_spec(), loss="sparse_softmax_cross_entropy",
        worker_optimizer="sgd", learning_rate=0.05,
        num_workers=2, batch_size=16, communication_window=2,
        num_epoch=2, backend="ps", ps_transport="inprocess",
        elastic=True, autoscale_target=policy,
        watch=True, scrape_interval=0.05,
        watch_dir=str(tmp_path / "watch"),
    )
    t.train(ds, shuffle=True)
    doc = json.loads(open(t.watch_path_).read())
    worker_series = [n for n in doc["series"]
                     if n.startswith("worker.") and n.endswith(".windows")]
    assert worker_series, sorted(doc["series"])
    # over-target with a tiny target: the policy was driven off the
    # shared series (it recorded decisions only the store path fed)
    elastic = t.resilience_stats_["elastic"]
    assert elastic["assigner"]["exactly_once"]
