"""Expert parallelism (MoE + all_to_all) vs the single-device oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distkeras_tpu.parallel.expert import (
    init_moe_params,
    moe_mlp,
    moe_mlp_reference,
)
from distkeras_tpu.parallel.tensor import get_mesh_nd

D, H, E, T = 16, 32, 8, 64


def test_reference_routes_to_argmax_expert(rng):
    """Top-1 MoE output == gate-prob-weighted output of the argmax expert."""
    params = init_moe_params(rng, D, H, E, scale=0.2)
    x = rng.normal(size=(T, D)).astype(np.float32)
    y, aux = moe_mlp_reference(params, x, top_k=1)

    logits = x @ params["gate"]
    probs = jax.nn.softmax(logits, axis=-1)
    picks = np.argmax(logits, axis=-1)
    for i in range(T):
        e = picks[i]
        h = jax.nn.gelu(x[i] @ params["w1"][e] + params["b1"][e])
        want = (h @ params["w2"][e] + params["b2"][e]) * probs[i, e] / probs[
            i, e
        ]  # top-1 renormalizes to weight 1.0
        np.testing.assert_allclose(np.asarray(y[i]), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)
    assert np.isfinite(float(aux))


@pytest.mark.parametrize("top_k", [
    # top_k=2 is the production-shaped oracle and exercises the same
    # routing machinery; the top_k=1 variant rides the slow tier
    pytest.param(1, marks=pytest.mark.slow), 2,
])
def test_mesh_matches_reference(rng, top_k):
    assert len(jax.devices()) == 8
    mesh = get_mesh_nd({"ep": 8})
    params = init_moe_params(rng, D, H, E, scale=0.2)
    x = rng.normal(size=(T, D)).astype(np.float32)
    # capacity_factor = E/top_k → capacity = t_local, nothing can drop
    y, _ = moe_mlp(params, x, mesh, top_k=top_k, capacity_factor=E / top_k)
    ref, _ = moe_mlp_reference(params, x, top_k=top_k)
    assert len(y.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_capacity_overflow_drops_tokens(rng):
    """All tokens forced to expert 0 with capacity 1/shard → one survivor
    per shard, the GShard drop semantics."""
    mesh = get_mesh_nd({"ep": 8})
    params = init_moe_params(rng, D, H, E, scale=0.2)
    params["gate"] = np.zeros((D, E), np.float32)
    params["gate"][:, 0] = 10.0  # every token's argmax is expert 0
    x = np.abs(rng.normal(size=(T, D))).astype(np.float32) + 0.5
    # t_local = 8; capacity_factor s.t. capacity = 1
    y, _ = moe_mlp(params, x, mesh, top_k=1, capacity_factor=1.0)
    rows = np.asarray(jnp.sum(jnp.abs(y), axis=-1))
    assert int(np.sum(rows > 1e-7)) == 8  # exactly one token per shard kept


def test_gradients_flow(rng):
    mesh = get_mesh_nd({"ep": 8})
    params = init_moe_params(rng, D, H, E, scale=0.2)
    x = rng.normal(size=(T, D)).astype(np.float32)

    def loss(params):
        y, aux = moe_mlp(params, x, mesh, top_k=2, capacity_factor=4.0)
        return jnp.mean(y ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    for k, leaf in g.items():
        n = float(jnp.sum(jnp.abs(leaf)))
        assert np.isfinite(n), k
        assert n > 0, f"zero grad for {k}"


def test_moe_trains_to_fit_target(rng):
    """The full layer learns a simple map through the sharded path."""
    mesh = get_mesh_nd({"ep": 8})
    params = init_moe_params(rng, D, H, E, scale=0.2)
    x = rng.normal(size=(T, D)).astype(np.float32)
    target = np.roll(x, 1, axis=1) * 0.5

    def loss(params):
        y, aux = moe_mlp(params, x, mesh, top_k=2, capacity_factor=4.0)
        return jnp.mean((y - target) ** 2) + 0.01 * aux

    tx = optax.adam(3e-3)
    opt = tx.init(params)
    losses = []
    step = jax.jit(lambda p, o: _step(loss, tx, p, o))
    for _ in range(60):
        params, opt, l = step(params, opt)
        losses.append(float(l))
    assert losses[-1] < 0.5 * losses[0]


def test_validation_errors(rng):
    mesh = get_mesh_nd({"ep": 8})
    params = init_moe_params(rng, D, H, 6)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="experts"):
        moe_mlp(params, np.zeros((T, D), np.float32), mesh)
    params = init_moe_params(rng, D, H, E)
    with pytest.raises(ValueError, match="tokens"):
        moe_mlp(params, np.zeros((T + 1, D), np.float32), mesh)


@pytest.mark.slow
def test_moe_transformer_mesh_matches_reference(rng):
    """Full MoE model: expert-parallel forward == single-device forward."""
    from distkeras_tpu.models.moe import MoETransformerClassifier

    mesh = get_mesh_nd({"ep": 8})
    kw = dict(vocab=64, maxlen=16, dim=D, heads=4, depth=2, num_experts=E,
              top_k=2, capacity_factor=E / 2,  # no drops → exact equality
              num_classes=4, dtype=jnp.float32)
    plain = MoETransformerClassifier(**kw)
    sharded = MoETransformerClassifier(**kw, mesh=mesh)
    toks = rng.integers(0, 64, size=(4, 16)).astype(np.int32)
    mask = np.ones((4, 16), np.float32)
    variables = plain.init(jax.random.PRNGKey(0), toks, mask, training=False)

    ref = plain.apply(variables, toks, mask, False)
    out = sharded.apply(variables, toks, mask, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # MoE training integration; gradient-flow + mesh oracle stay fast
def test_moe_transformer_trains_with_aux_loss(rng):
    from distkeras_tpu.models.moe import (
        MoETransformerClassifier,
        moe_aux_loss,
    )

    module = MoETransformerClassifier(
        vocab=64, maxlen=16, dim=D, heads=4, depth=2, num_experts=E,
        top_k=2, num_classes=4, dtype=jnp.float32,
    )
    n = 32
    y = rng.integers(0, 4, size=(n,)).astype(np.int32)
    toks = (y[:, None] * 16 + rng.integers(0, 16, size=(n, 16))).astype(
        np.int32
    )
    mask = np.ones((n, 16), np.float32)
    params = module.init(
        jax.random.PRNGKey(0), toks, mask, training=False
    )["params"]

    def loss(params):
        logits, aux = moe_aux_loss(module, params, (toks, mask))
        logp = jax.nn.log_softmax(logits)
        ce = -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))
        return ce + 0.01 * aux

    tx = optax.adam(2e-3)
    opt = tx.init(params)
    step = jax.jit(lambda p, o: _step(loss, tx, p, o))
    losses = []
    for _ in range(25):
        params, opt, l = step(params, opt)
        losses.append(float(l))
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.6 * losses[0]


@pytest.mark.slow  # trainer-API integration; gradient-flow + mesh oracle stay fast
def test_moe_model_trains_through_trainer_api(rng):
    """The MoE family is a first-class citizen of the reference trainer API:
    ADAG over stacked workers vmaps the (single-device-math) MoE blocks."""
    import jax.numpy as jnp

    from distkeras_tpu.data import Dataset
    from distkeras_tpu.models import moe_transformer_classifier
    from distkeras_tpu.trainers import ADAG

    n, maxlen, classes = 64, 16, 4
    y = rng.integers(0, classes, size=(n,)).astype(np.int32)
    toks = (y[:, None] * 16 + rng.integers(0, 16, size=(n, maxlen))).astype(
        np.int32
    )
    ds = Dataset({
        "features": toks,
        "mask": np.ones((n, maxlen), np.float32),
        "label": y,
    })
    spec = moe_transformer_classifier(
        vocab=64, maxlen=maxlen, dim=16, heads=2, depth=1, num_experts=4,
        top_k=2, num_classes=classes, dtype=jnp.float32,
    )
    trainer = ADAG(
        spec, loss="sparse_softmax_cross_entropy", worker_optimizer="adam",
        learning_rate=2e-3, num_workers=2, batch_size=8,
        communication_window=2, num_epoch=8,
        features_col=["features", "mask"], label_col="label",
    )
    trainer.train(ds, shuffle=True)
    losses = trainer.history.losses()
    assert np.isfinite(losses).all()
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def _step(loss, tx, params, opt):
    l, g = jax.value_and_grad(loss)(params)
    u, opt = tx.update(g, opt, params)
    return optax.apply_updates(params, u), opt, l


def test_moe_composes_with_data_parallel(rng):
    """dp×ep on one 2-D mesh: only 'ep' is mapped manually, the outer
    program shards tokens over dp too — same values as the oracle."""
    from distkeras_tpu.parallel.tensor import get_mesh_nd

    mesh = get_mesh_nd({"dp": 2, "ep": 4})
    E = 8
    params = init_moe_params(rng, 16, 32, E, scale=0.2)
    x = rng.normal(size=(64, 16)).astype(np.float32)

    @jax.jit
    def run(params, x):
        y, aux = moe_mlp(params, x, mesh, top_k=2, capacity_factor=E / 2)
        return y, aux

    y, aux = run(params, x)
    ref, _ = moe_mlp_reference(params, x, top_k=2)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)
    # differentiable through the composed layout
    g = jax.grad(lambda p: run(p, x)[0].sum() + 0.01 * run(p, x)[1])(params)
    gn = sum(float(jnp.sum(l ** 2)) for l in jax.tree.leaves(g))
    assert np.isfinite(gn) and gn > 0
