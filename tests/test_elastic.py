"""Elastic scale-out: live join, preemption drain, autoscaler (ISSUE 9).

The acceptance oracle threaded through this file: under seeded mid-run
JOINS and PREEMPTIONS a PS run must (a) complete, (b) converge below the
no-fault first-epoch loss, (c) train every example exactly once per epoch
across every membership boundary (the ShardAssigner ledger), and (d) fold
every logical commit exactly once per shard (``num_updates`` == logical
commits — joiners' fresh seqno streams and drained workers' retired
seqnos included). Pool membership must be visible in ``ps.stats()``
(``pool_size`` / ``joined_workers`` / ``preempted_workers`` /
``drain_timeouts``) on every transport.
"""

import threading
import time
import warnings

import numpy as np
import pytest

import distkeras_tpu as dk
from distkeras_tpu.parallel.merge_rules import DownpourMerge, DynSGDMerge
from distkeras_tpu.parameter_servers import (
    ParameterServer,
    ParameterServerClient,
    SocketParameterServer,
)
from distkeras_tpu.resilience import (
    ElasticCoordinator,
    ElasticPolicy,
    FaultPlan,
    RetryPolicy,
    ShardAssigner,
)
from tests.test_trainers import blobs_dataset, model_spec


def epoch_mean_loss(trainer, epoch):
    """Mean loss over one epoch's windows. Elastic histories are hogwild
    ACROSS epochs: a drained straggler's early-epoch window can be the
    last record appended (its commit sat in retries while the survivors
    finished), so 'last N records' is not a convergence metric here."""
    return float(np.mean([
        r["loss"] for r in trainer.get_history()
        if "loss" in r and r.get("epoch") == epoch
    ]))


# ---------------------------------------------------------------------------
# FaultPlan: deterministic join/preempt events
# ---------------------------------------------------------------------------


def test_fault_plan_join_preempt_fire_once_each():
    plan = FaultPlan(join_worker_at_window={0: 2},
                     preempt_worker_at_window={1: 4})
    assert plan.has_elastic_events
    assert not plan.take_join(0, 1)       # not yet
    assert not plan.take_join(1, 2)       # wrong worker
    assert plan.take_join(0, 2)           # fires
    assert not plan.take_join(0, 2)       # once only (a replay is safe)
    assert not plan.take_preempt(1, 2)
    assert plan.take_preempt(1, 4)
    assert not plan.take_preempt(1, 4)
    s = plan.stats()
    assert s["joins"] == 1 and s["preempts"] == 1
    assert not FaultPlan(kill_at={0: 1}).has_elastic_events


def test_fault_plan_event_ordering_is_window_deterministic():
    """Events key on (worker, completed-window count) — the same seam as
    kill_at — so replaying the window sequence replays the event order
    exactly."""
    plan = FaultPlan(join_worker_at_window={0: 1},
                     preempt_worker_at_window={0: 3})
    order = []
    for w in range(1, 5):
        if plan.take_join(0, w):
            order.append(("join", w))
        if plan.take_preempt(0, w):
            order.append(("preempt", w))
    assert order == [("join", 1), ("preempt", 3)]


# ---------------------------------------------------------------------------
# ShardAssigner: the exactly-once-per-epoch oracle
# ---------------------------------------------------------------------------


def test_assigner_fixed_pool_exactly_once_with_full_coverage():
    a = ShardAssigner(n_rows=64, window=2, batch_size=4, num_epoch=2,
                      seed=3, shuffle=True)
    assert a.blocks_per_epoch == 8
    seen: dict[int, list] = {0: [], 1: []}
    while True:
        task = a.claim(0)
        if task is None:
            break
        e, b, idx = task
        seen[e].append(idx)
        a.complete(0, e, b)
    o = a.oracle()
    assert o["exactly_once"] and o["blocks_done"] == 16
    for e in (0, 1):
        rows = np.concatenate(seen[e])
        assert len(rows) == len(set(rows.tolist())) == 64  # no dup, no drop
        np.testing.assert_array_equal(np.sort(rows), np.arange(64))
    # shuffle: the two epochs draw different orders from (seed, epoch)
    assert not np.array_equal(np.concatenate(seen[0]),
                              np.concatenate(seen[1]))


def test_assigner_exactly_once_across_join_and_drain():
    """The membership-change oracle: worker 0 starts, worker 1 joins
    mid-epoch, worker 0 is drained holding an in-flight block — the
    block goes back and worker 1 finishes it. No example dropped or
    duplicated."""
    a = ShardAssigner(n_rows=48, window=1, batch_size=8, num_epoch=1)
    covered = []
    e0, b0, idx0 = a.claim(0)
    # worker 0 trains one block to completion...
    a.complete(0, e0, b0)
    covered.append(idx0)
    # ...claims another, then is drained BEFORE confirming it
    _, b_hold, _ = a.claim(0)
    assert a.release(0) == 1              # the unconfirmed block goes back
    assert a.oracle()["released_blocks"] == 1
    # worker 1 joins and drains the rest of the pool — including b_hold
    blocks_seen = set()
    while True:
        task = a.claim(1)
        if task is None:
            break
        e, b, idx = task
        blocks_seen.add(b)
        covered.append(idx)
        a.complete(1, e, b)
    assert b_hold in blocks_seen          # the handed-back range retrained
    o = a.oracle()
    assert o["exactly_once"], o
    rows = np.concatenate(covered)
    np.testing.assert_array_equal(np.sort(rows), np.arange(48))


def test_assigner_claim_blocks_until_release_then_drains():
    """A worker whose pool is all in-flight WAITS (the holder might drain
    and hand blocks back) instead of dropping work or spinning out."""
    a = ShardAssigner(n_rows=8, window=1, batch_size=8, num_epoch=1)
    assert a.blocks_per_epoch == 1
    a.claim(0)                            # worker 0 holds the only block
    got = []

    def waiter():
        got.append(a.claim(1))

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.15)
    assert not got                        # parked, not None
    a.release(0)                          # worker 0 drains
    t.join(timeout=5)
    assert got and got[0] is not None     # the waiter inherited the block
    e, b, _ = got[0]
    a.complete(1, e, b)
    assert a.claim(1) is None             # now genuinely done
    assert a.oracle()["exactly_once"]


def test_assigner_stale_completion_after_forced_release():
    """A timeout-drained worker's late complete() is refused and counted:
    the block belongs to its new owner, and the ledger honestly reports
    the at-least-once window."""
    a = ShardAssigner(n_rows=16, window=1, batch_size=8, num_epoch=1)
    e, b, _ = a.claim(0)
    a.release(0)                          # forced release (drain deadline)
    assert a.complete(0, e, b) is False   # the zombie's confirm bounces
    e1, b1, _ = a.claim(1)
    assert (e1, b1) == (e, b)
    a.complete(1, e1, b1)
    task = a.claim(1)
    a.complete(1, task[0], task[1])
    o = a.oracle()
    assert o["stale_completions"] == 1 and not o["exactly_once"]


def test_assigner_respects_start_epoch():
    a = ShardAssigner(n_rows=16, window=1, batch_size=8, num_epoch=3,
                      start_epoch=2)
    epochs = set()
    while True:
        task = a.claim(0)
        if task is None:
            break
        epochs.add(task[0])
        a.complete(0, task[0], task[1])
    assert epochs == {2}


# ---------------------------------------------------------------------------
# ElasticPolicy: the autoscaler's decisions
# ---------------------------------------------------------------------------


def test_policy_grows_under_target_and_shrinks_over_it():
    p = ElasticPolicy(target_rounds_per_sec=10.0, max_workers=4,
                      cooldown_s=0.0)
    assert p.observe(0.0, {0: 0, 1: 0}) == []        # baseline sample
    # 4 rounds/s total < 8.5 → join
    assert p.observe(1.0, {0: 2, 1: 2}) == [("join", None)]
    # 20 rounds/s total > 13 → release the slowest
    assert p.observe(2.0, {0: 14, 1: 10, 2: 0}) == [("release", 2)]
    assert [d["action"] for d in p.decisions] == ["join", "release"]


def test_policy_releases_persistent_straggler_only_after_patience():
    p = ElasticPolicy(patience=2, cooldown_s=0.0)    # no target: τ-tail only
    p.observe(0.0, {0: 0, 1: 0, 2: 0})
    assert p.observe(1.0, {0: 10, 1: 10, 2: 0}) == []   # 1 slow obs: wait
    assert p.observe(2.0, {0: 20, 1: 20, 2: 0}) == [("release", 2)]
    # a recovered worker resets its patience counter
    p2 = ElasticPolicy(patience=2, cooldown_s=0.0)
    p2.observe(0.0, {0: 0, 1: 0})
    p2.observe(1.0, {0: 10, 1: 0})
    p2.observe(2.0, {0: 20, 1: 10})                   # caught back up
    assert p2.observe(3.0, {0: 30, 1: 10}) == []      # counter restarted


def test_policy_cooldown_and_max_workers():
    p = ElasticPolicy(target_rounds_per_sec=100.0, max_workers=2,
                      cooldown_s=10.0)
    p.observe(0.0, {0: 0})
    assert p.observe(1.0, {0: 1}) == [("join", None)]
    assert p.observe(2.0, {0: 2, 1: 0}) == []         # in cooldown
    assert p.observe(13.0, {0: 3, 1: 1}) == []        # at max_workers
    with pytest.raises(ValueError, match="max_workers"):
        ElasticPolicy(min_workers=3, max_workers=2)


# ---------------------------------------------------------------------------
# The join/drain protocol + pool stats, per transport
# ---------------------------------------------------------------------------


def test_join_and_drain_counters_inprocess():
    ps = ParameterServer({"w": np.zeros(2, np.float32)}, DownpourMerge(), 2)
    s = ps.stats()
    assert s["pool_size"] == 2 and s["joined_workers"] == 0
    rec = ps.join_worker(5)
    assert rec["pool_size"] == 3
    assert ps._registry.active() == [5]   # leased, quietly
    assert ps.stats()["heartbeats"] == 0  # join is NOT a heartbeat
    ps.drain_worker(5)
    s = ps.stats()
    assert s["pool_size"] == 2
    assert s["joined_workers"] == 1 and s["preempted_workers"] == 1
    assert s["drain_timeouts"] == 0 and s["evicted_workers"] == 0
    ps.drain_worker(7, timeout=True)      # the force-drain path
    s = ps.stats()
    assert s["drain_timeouts"] == 1 and s["preempted_workers"] == 2


def test_join_and_drain_are_lost_ack_replay_safe():
    """The membership analogue of commit seqno dedup: join/drain ride
    lossy links, and a retried action whose ACK died must not
    double-count the event — until the wid's membership actually flips
    again (drain → join → drain all recount)."""
    ps = ParameterServer({"w": np.zeros(2, np.float32)}, DownpourMerge(), 2)
    ps.join_worker(4)
    ps.join_worker(4)                     # replay: no double-count
    s = ps.stats()
    assert s["joined_workers"] == 1 and s["pool_size"] == 3
    ps.drain_worker(4)
    ps.drain_worker(4)                    # replay: no double-count
    s = ps.stats()
    assert s["preempted_workers"] == 1 and s["pool_size"] == 2
    ps.join_worker(4)                     # a REAL re-join counts again
    ps.drain_worker(4)
    s = ps.stats()
    assert s["joined_workers"] == 2 and s["preempted_workers"] == 2
    assert s["pool_size"] == 2


def test_join_and_drain_over_socket_wire_retires_dedup_seqno():
    ps = SocketParameterServer({"w": np.zeros(2, np.float32)},
                               DownpourMerge(), 1)
    ps.initialize()
    ps.start()
    try:
        c = ParameterServerClient("127.0.0.1", ps.port, 3)
        rec = c.join()
        assert rec["ok"] and rec["pool_size"] == 2
        c.commit(3, {"w": np.ones(2, np.float32)}, seq=9)
        assert 3 in ps._last_seq
        c.drain(timeout=False)
        assert 3 not in ps._last_seq      # the PR 5 bounded-table path
        s = ps.stats()
        assert s["pool_size"] == 1
        assert s["joined_workers"] == 1 and s["preempted_workers"] == 1
        c.close()
    finally:
        ps.stop()


def test_join_and_drain_over_shm_rings_retires_dedup_seqno():
    """ISSUE 12: the shm transport speaks the full elastic protocol —
    join/drain over the rings with the same pool accounting and
    bounded-dedup-table retirement as the socket wire."""
    from distkeras_tpu.shm import ShmParameterServer, ShmPSClient

    ps = ShmParameterServer({"w": np.zeros(2, np.float32)},
                            DownpourMerge(), 1)
    ps.initialize()
    ps.start()
    try:
        c = ShmPSClient(ps, 3)
        rec = c.join()
        assert rec["ok"] and rec["pool_size"] == 2
        c.commit(3, {"w": np.ones(2, np.float32)}, seq=9)
        assert 3 in ps._last_seq
        c.drain(timeout=False)
        assert 3 not in ps._last_seq      # the PR 5 bounded-table path
        s = ps.stats()
        assert s["pool_size"] == 1
        assert s["joined_workers"] == 1 and s["preempted_workers"] == 1
        c.close()
    finally:
        ps.stop()


def test_elastic_trainer_live_join_and_clean_preempt_shm():
    """ISSUE 12: the elastic trainer loop on ps_transport='shm' —
    build_client mints JOINER ring clients mid-run, the drained worker
    leaves cleanly, and the exactly-once ledger holds."""
    from distkeras_tpu.shm import ShmParameterServer  # noqa: F401

    ds = blobs_dataset(n=1024)
    plan = FaultPlan(seed=3, join_worker_at_window={0: 1},
                     preempt_worker_at_window={1: 1})
    t = dk.DOWNPOUR(model_spec(), **_kw(elastic=True, fault_plan=plan,
                                        ps_transport="shm",
                                        heartbeat_interval=0.1))
    t.train(ds, shuffle=True)
    el = t.resilience_stats_["elastic"]
    assert el["joined"] == 1 and el["preempted"] == 1
    assert el["assigner"]["exactly_once"], el["assigner"]
    s = t.ps_stats_
    assert s["joined_workers"] == 1 and s["preempted_workers"] == 1
    assert s["pool_size"] == 2            # 2 + 1 join − 1 drain
    assert s["commits"] == t.resilience_stats_["logical_commits"]
    workers_seen = {r.get("worker") for r in t.get_history() if "loss" in r}
    assert 2 in workers_seen              # the joiner trained over rings


def test_native_join_drain_protocol_parity():
    """The C++ transport speaks JOIN/DRAIN (actions 12/13) with the same
    pool accounting and the same stats key set as the Python PS."""
    from distkeras_tpu.native import load_dkps

    if load_dkps() is None:
        pytest.skip("no C++ toolchain to build libdkps")
    from distkeras_tpu.native_ps import (
        NativePSClient,
        NativeSocketParameterServer,
    )

    center = {"w": np.zeros(4, np.float32)}
    ps = NativeSocketParameterServer(center, DownpourMerge(), 2)
    ps.initialize()
    ps.start()
    try:
        c = NativePSClient("127.0.0.1", ps.port, 6, ps.spec)
        rec = c.join()
        assert rec["pool_size"] == 3 and rec["num_updates"] == 0
        assert ps.stats()["heartbeats"] == 0      # quiet admission
        c.commit(6, {"w": np.ones(4, np.float32)}, seq=1)
        c.drain(timeout=False)
        s = ps.stats()
        assert s["pool_size"] == 2
        assert s["joined_workers"] == 1 and s["preempted_workers"] == 1
        assert s["drain_timeouts"] == 0
        py = ParameterServer(center, DownpourMerge(), 2)
        assert set(s) == set(py.stats())          # key-set parity
        c.close()
    finally:
        ps.stop()


def test_joiner_dynsgd_tau_priced_from_join_pull_never_zero_version():
    """The live-join staleness contract: the joiner pulls AT JOIN, so its
    first commit is priced at the true small τ — not the maximal
    staleness a version-less worker would be charged."""
    ps = ParameterServer({"w": np.zeros(1, np.float32)}, DynSGDMerge(), 2)
    for _ in range(4):                    # incumbent trains: center = 16
        ps.pull(0)
        ps.commit(0, {"w": np.array([4.0], np.float32)})
    ps.join_worker(1)
    ps.pull(1)                            # pull-version initialized: 4
    ps.commit(1, {"w": np.array([5.0], np.float32)})   # τ = 0 → +5/1
    np.testing.assert_allclose(ps.get_model()["w"], 16.0 + 5.0)
    # contrast — a worker that NEVER pulled is priced at τ = num_updates
    ps2 = ParameterServer({"w": np.zeros(1, np.float32)}, DynSGDMerge(), 2)
    for _ in range(4):
        ps2.pull(0)
        ps2.commit(0, {"w": np.array([4.0], np.float32)})
    ps2.commit(1, {"w": np.array([5.0], np.float32)})  # τ = 4 → +5/5
    np.testing.assert_allclose(ps2.get_model()["w"], 16.0 + 1.0)


# ---------------------------------------------------------------------------
# ElasticCoordinator: the drain state machine (stub workers)
# ---------------------------------------------------------------------------


class _StubClient:
    def __init__(self):
        self.drains: list[bool] = []
        self.closed = False

    def drain(self, timeout=False):
        self.drains.append(bool(timeout))

    def close(self):
        self.closed = True


def _stub_spawn_factory(bodies):
    """spawn() over plain threads: bodies[wid](worker) is the 'training
    loop'."""
    def spawn(wid, joiner):
        class W:
            drain_event = threading.Event()
            error = None
            _windows_done = 0

        w = W()
        c = _StubClient()
        t = threading.Thread(target=bodies[wid], args=(w,), daemon=True)
        t.start()
        return w, c, t

    return spawn


def test_coordinator_clean_drain_reports_and_settles():
    a = ShardAssigner(n_rows=8, window=1, batch_size=8, num_epoch=1)

    def cooperative(w):
        w.drain_event.wait(10)            # exits promptly on the notice

    co = ElasticCoordinator(
        a, _stub_spawn_factory({0: cooperative}), drain_timeout=5.0,
        poll_interval=0.02,
    )
    co.start([0])
    assert co.request_preempt(0)
    assert not co.request_preempt(0)      # idempotent while draining
    co.run()
    s = co.stats()
    assert s["preempted"] == 1 and s["drain_timeouts"] == 0
    assert co.clients[0].drains == [False]
    assert not co.clients[0].closed       # common shutdown path owns close


def test_coordinator_drain_deadline_falls_back_to_force_drain():
    a = ShardAssigner(n_rows=8, window=1, batch_size=8, num_epoch=1)
    a.claim(0)                            # the wedged worker holds a block
    unwedge = threading.Event()
    admin = _StubClient()

    def wedged(w):
        unwedge.wait(30)                  # ignores the drain notice

    co = ElasticCoordinator(
        a, _stub_spawn_factory({0: wedged}),
        make_drain_client=lambda wid: admin,
        drain_timeout=0.2, poll_interval=0.02,
    )
    co.start([0])
    co.request_preempt(0)
    co.run()                              # abandoned thread excluded
    s = co.stats()
    assert s["drain_timeouts"] == 1 and s["preempted"] == 1
    assert admin.drains == [True]         # reported with timeout=True
    assert admin.closed
    assert co.clients[0].closed           # torn out from under the wedge
    # the wedged worker's shard range went back to the pool
    assert a.oracle()["blocks_in_flight"] == 0
    assert a.claim(1) is not None
    # whatever the abandoned worker raises later is not a run failure
    co.workers[0].error = RuntimeError("post-abandon fallout")
    assert co.worker_error(co.workers[0]) is None
    unwedge.set()


# ---------------------------------------------------------------------------
# Trainer integration
# ---------------------------------------------------------------------------


def _kw(**extra):
    kw = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
              learning_rate=0.05, num_workers=2, batch_size=16,
              communication_window=2, num_epoch=2, backend="ps")
    kw.update(extra)
    return kw


def test_elastic_trainer_live_join_and_clean_preempt():
    """A join and a preemption on the in-process transport: the joiner
    contributes history, the drained worker leaves cleanly, the
    exactly-once ledger and the pool counters all agree."""
    ds = blobs_dataset(n=1024)
    # threshold 1 fires unconditionally: a live worker always completes
    # >= 1 window (it holds a claimed block its peers wait on), while a
    # higher threshold can starve under 1-core thread scheduling
    plan = FaultPlan(seed=3, join_worker_at_window={0: 1},
                     preempt_worker_at_window={1: 1})
    t = dk.DOWNPOUR(model_spec(), **_kw(elastic=True, fault_plan=plan,
                                        heartbeat_interval=0.1))
    t.train(ds, shuffle=True)
    el = t.resilience_stats_["elastic"]
    assert el["joined"] == 1 and el["preempted"] == 1
    assert el["drain_timeouts"] == 0
    assert el["assigner"]["exactly_once"], el["assigner"]
    s = t.ps_stats_
    assert s["joined_workers"] == 1 and s["preempted_workers"] == 1
    assert s["pool_size"] == 2            # 2 + 1 join − 1 drain
    # every logical commit folded exactly once (joiner + drainee incl.)
    assert s["commits"] == t.resilience_stats_["logical_commits"]
    workers_seen = {r.get("worker") for r in t.get_history() if "loss" in r}
    assert 2 in workers_seen              # the joiner trained for real
    assert epoch_mean_loss(t, 1) < 0.6


def test_elastic_autoscaler_joins_toward_target():
    """An unreachably-high rounds/s target makes the autoscaler grow the
    pool through the live-join path up to max_pool_size."""
    ds = blobs_dataset(n=2048)
    policy = ElasticPolicy(target_rounds_per_sec=1e6, max_workers=3,
                           cooldown_s=0.0)
    t = dk.DOWNPOUR(model_spec(), **_kw(elastic=True,
                                        autoscale_target=policy,
                                        max_pool_size=3))
    t.train(ds, shuffle=True)
    el = t.resilience_stats_["elastic"]
    assert el["joined"] >= 1
    assert any(d["reason"] == "under_target"
               for d in el["policy_decisions"])
    assert el["assigner"]["exactly_once"]
    assert t.ps_stats_["joined_workers"] == el["joined"]


def test_elastic_resume_reconciles_with_warn_elastic_resume(tmp_path):
    """The checkpoint.py reconcile: an elastic trainer resuming any
    checkpoint takes the elastic-resume path (center carries over, fresh
    per-worker state, warn_elastic_resume fired) and trains only the
    remaining epochs — exactly once each."""
    ds = blobs_dataset(n=512)
    t1 = dk.DOWNPOUR(model_spec(), **_kw(num_epoch=1,
                                         checkpoint_dir=str(tmp_path)))
    t1.train(ds, shuffle=True)
    t2 = dk.DOWNPOUR(model_spec(), **_kw(num_workers=4, num_epoch=2,
                                         elastic=True,
                                         checkpoint_dir=str(tmp_path),
                                         resume=True))
    with pytest.warns(UserWarning, match="elastic resume"):
        t2.train(ds, shuffle=True)
    el = t2.resilience_stats_["elastic"]
    assert el["assigner"]["epochs"] == 1  # only epoch 1 remained
    assert el["assigner"]["exactly_once"]
    epochs = {r["epoch"] for r in t2.get_history() if "loss" in r}
    assert epochs == {1}
    # and an elastic run does not WRITE barrier checkpoints
    t3 = dk.DOWNPOUR(model_spec(), **_kw(elastic=True, num_epoch=1,
                                         checkpoint_dir=str(tmp_path)))
    with pytest.warns(UserWarning, match="resume-only"):
        t3.train(ds, shuffle=True)


def test_elastic_knob_validation():
    with pytest.raises(ValueError, match="backend='ps'"):
        dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", num_workers=2, elastic=True)
    with pytest.raises(ValueError, match="autoscale_target requires"):
        dk.ADAG(model_spec(), **_kw(autoscale_target=10.0))
    with pytest.raises(ValueError, match="max_pool_size requires"):
        dk.ADAG(model_spec(), **_kw(max_pool_size=4))
    with pytest.raises(ValueError, match="mutually exclusive"):
        dk.ADAG(model_spec(), **_kw(elastic=True, worker_restart_budget=1))
    with pytest.raises(ValueError, match="preempt_drain_timeout"):
        dk.ADAG(model_spec(), **_kw(elastic=True, preempt_drain_timeout=0))
    with pytest.raises(ValueError, match="must be >= num_workers"):
        dk.ADAG(model_spec(), **_kw(elastic=True, max_pool_size=1))
    # a plan carrying membership events needs an elastic trainer
    plan = FaultPlan(join_worker_at_window={0: 1})
    t = dk.ADAG(model_spec(), **_kw(fault_plan=plan))
    with pytest.raises(ValueError, match="join/preempt"):
        t.train(blobs_dataset(n=512), shuffle=True)


# ---------------------------------------------------------------------------
# The chaos integration test (acceptance criterion)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cls_name,shards", [
    ("ADAG", 1), ("DOWNPOUR", 2), ("DynSGD", 1),
])
def test_elastic_chaos_converges_exactly_once(cls_name, shards, tmp_path):
    """ADAG/DOWNPOUR/DynSGD under elastic chaos — a seeded mid-run JOIN
    and PREEMPTION plus wire drops/delays, socket transport, WAL on, the
    2-shard leg included — must complete, converge below the no-fault
    first-epoch loss, satisfy the every-example-exactly-once ledger, and
    fold every logical commit exactly once PER SHARD (no double-folds
    from joiners or drained workers)."""
    cls = getattr(dk, cls_name)
    ds = blobs_dataset(n=1024)

    # no-fault baseline: its FIRST-epoch loss is the convergence bar
    base = cls(model_spec(), **_kw())
    base.train(ds, shuffle=True)
    first_epoch = epoch_mean_loss(base, 0)

    # threshold-1 events (>= semantics) fire unconditionally: a live
    # worker always completes >= 1 window (it holds a claimed block its
    # peers wait on), even when the wire chaos concentrates its retry
    # stalls on the event's observer
    plan = FaultPlan(seed=13, drop_recv=0.03, delay=0.03, delay_s=0.002,
                     max_faults=40,
                     join_worker_at_window={0: 1},
                     preempt_worker_at_window={1: 1})
    t = cls(model_spec(), **_kw(
        ps_transport="socket", ps_num_shards=shards,
        ps_wal_dir=str(tmp_path / "wal"), elastic=True, fault_plan=plan,
        retry_policy=RetryPolicy(base_delay=0.005, max_delay=0.1,
                                 deadline=60),
        heartbeat_interval=0.05,
    ))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with plan:
            t.train(ds, shuffle=True)

    # (a) completed with the membership chaos actually injected
    st = plan.stats()
    assert st["joins"] == 1 and st["preempts"] == 1
    assert st["drops"] > 0                # the wire chaos bit too
    rs = t.resilience_stats_
    el = rs["elastic"]
    assert el["joined"] == 1 and el["preempted"] == 1
    assert el["drain_timeouts"] == 0      # the drain beat its deadline
    # (b) converged: the chaos run's FINAL epoch beats the clean run's
    # first epoch (per-epoch means — see epoch_mean_loss)
    chaos_final = epoch_mean_loss(t, 1)
    assert chaos_final < first_epoch, (chaos_final, first_epoch)
    # (c) every example exactly once per epoch across the join/drain
    assert el["assigner"]["exactly_once"], el["assigner"]
    # (d) exactly-once folds per shard: lifetime fold count == logical
    # commits, on EVERY shard (min == max for the sharded leg)
    s = t.ps_stats_
    assert s["num_updates"] == rs["logical_commits"]
    if shards > 1:
        assert s["num_updates"] == s["num_updates_max"]
    # pool membership visible through the stats roll-up
    assert s["joined_workers"] == 1 and s["preempted_workers"] == 1
    assert s["drain_timeouts"] == 0
    # the joiner contributed post-join history
    workers_seen = {r.get("worker") for r in t.get_history() if "loss" in r}
    assert 2 in workers_seen
