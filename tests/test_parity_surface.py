"""Parity-surface regressions: alias package, loss-name semantics, datasets."""

import numpy as np


def test_distkeras_alias_package():
    import distkeras
    from distkeras.trainers import ADAG, SingleTrainer  # noqa: F401
    from distkeras.utils import serialize_weights  # noqa: F401
    import distkeras.transformers as T

    assert hasattr(T, "OneHotTransformer")
    assert distkeras.__version__


def test_sparse_categorical_crossentropy_is_probability_form(rng):
    from distkeras_tpu.ops import losses

    probs = rng.uniform(0.05, 1.0, size=(8, 5)).astype(np.float32)
    probs /= probs.sum(-1, keepdims=True)
    labels = rng.integers(0, 5, 8).astype(np.int32)
    expected = -np.log(probs[np.arange(8), labels]).mean()
    got = losses.get_loss("sparse_categorical_crossentropy")(labels, probs)
    assert np.isclose(float(got), expected, rtol=1e-4)


def test_drop_remainder_false_covers_every_row():
    from distkeras_tpu.data import Dataset

    ds = Dataset({"x": np.arange(100, dtype=np.float32)})
    batches = list(ds.batches(32, ["x"], drop_remainder=False))
    seen = np.concatenate([b[0] for b in batches])
    assert set(seen.astype(int).tolist()) == set(range(100))
    # and shapes stay static
    assert all(b[0].shape == (32,) for b in batches)


def test_synthetic_datasets_share_distribution_across_splits():
    """Train/test must come from the same class-conditional distribution —
    a nearest-class-template probe trained on train stats must transfer."""
    from distkeras_tpu import datasets

    train, test = datasets.mnist(n_train=2000, n_test=500)
    # per-class means from train
    classes = np.unique(train["label"])
    means = np.stack([
        train["features"][train["label"] == c].mean(0) for c in classes
    ])
    flat_means = means.reshape(len(classes), -1)
    xte = test["features"].reshape(len(test), -1)
    d = ((xte[:, None, :] - flat_means[None]) ** 2).sum(-1)
    acc = (classes[np.argmin(d, 1)] == test["label"]).mean()
    assert acc > 0.9, f"template transfer accuracy {acc}"


def test_higgs_boundary_shared_across_splits():
    from distkeras_tpu import datasets

    train, test = datasets.higgs(n_train=4000, n_test=1000)
    # linear probe: closed-form least squares on train, eval on test —
    # test accuracy must be above chance AND match train accuracy
    # (i.e. the decision boundary transfers across splits)
    xtr = np.c_[train["features"], np.ones(len(train))]
    ytr = train["label"].astype(np.float32)
    w, *_ = np.linalg.lstsq(xtr, ytr, rcond=None)
    xte = np.c_[test["features"], np.ones(len(test))]
    acc_tr = ((xtr @ w > 0.5).astype(int) == train["label"]).mean()
    acc_te = ((xte @ w > 0.5).astype(int) == test["label"]).mean()
    assert acc_te > 0.62, f"linear probe transfer accuracy {acc_te}"
    assert abs(acc_tr - acc_te) < 0.08, (acc_tr, acc_te)


def test_aeasgd_warns_on_unstable_alpha():
    import warnings
    from distkeras_tpu.parallel.merge_rules import ElasticAverageMerge

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ElasticAverageMerge(alpha=0.2, num_workers=8)
    assert any("overshoot" in str(x.message) for x in w)


def test_ps_backend_available_and_trains():
    import jax.numpy as jnp
    from distkeras_tpu import ADAG
    from distkeras_tpu.data import Dataset
    from distkeras_tpu.models import mlp

    ds = Dataset({"features": np.random.default_rng(0).normal(
                      size=(64, 4)).astype(np.float32),
                  "label": np.zeros(64, np.int32)})
    t = ADAG(mlp(input_shape=(4,), hidden=(8,), num_classes=2,
                 dtype=jnp.float32),
             loss="sparse_softmax_cross_entropy", num_workers=1,
             batch_size=16, communication_window=2, backend="ps")
    t.train(ds)
    assert len(t.get_history()) > 0


def test_reference_from_import_form_for_every_module():
    """`from distkeras.<module> import <Name>` — the reference's exact
    import style — must work for EVERY module, including the ones that used
    to be lazily bound (submodule import never consults module __getattr__,
    so registration must be eager)."""
    import subprocess
    import sys

    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "from distkeras.evaluators import AccuracyEvaluator;"
        "from distkeras.predictors import ModelPredictor;"
        "from distkeras.workers import AsyncWorker;"
        "from distkeras.parameter_servers import SocketParameterServer;"
        "from distkeras.networking import determine_host_address;"
        "from distkeras.job_deployment import Job, LocalRunner;"
        "from distkeras.checkpoint import save_checkpoint;"
        "import distkeras;"
        "assert not hasattr(distkeras, 'nope');"
        "print('ok')"
    )
    # a fresh interpreter proves it works without any prior attribute access
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-1500:]
    assert proc.stdout.strip().endswith("ok")
