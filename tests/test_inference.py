"""Inference-path coverage: ModelPredictor (incl. the pad-and-trim path),
LabelIndexPredictor, and the evaluators (SURVEY.md §3.4 parity surface)."""

import numpy as np
import pytest

from distkeras_tpu.data import Dataset
from distkeras_tpu.evaluators import AccuracyEvaluator, LossEvaluator
from distkeras_tpu.predictors import LabelIndexPredictor, ModelPredictor
from tests.test_trainers import blobs_dataset, model_spec


@pytest.fixture(scope="module")
def trained():
    """A spec + params good enough to beat chance on the blobs."""
    from distkeras_tpu import SingleTrainer

    ds = blobs_dataset(n=1024)
    t = SingleTrainer(model_spec(), loss="sparse_softmax_cross_entropy",
                      worker_optimizer="sgd", learning_rate=0.1,
                      batch_size=64, num_epoch=3)
    t.train(ds)
    return t.spec, t.trained_params_, t.trained_nt_


def test_predictor_pad_path_matches_direct_apply(trained):
    """n not divisible by batch_size: pad rows must be trimmed, predictions
    must equal a direct un-padded apply."""
    spec, params, nt = trained
    ds = blobs_dataset(n=70, seed=5)
    pred = ModelPredictor(spec, params, nt, batch_size=32)
    out = pred.predict(ds)
    assert out["prediction"].shape == (70, 4)
    direct, _ = spec.apply(params, nt, ds["features"], False)
    np.testing.assert_allclose(out["prediction"], np.asarray(direct),
                               rtol=1e-5, atol=1e-5)
    # original dataset untouched (with_column returns a new frame)
    assert "prediction" not in ds


def test_predictor_exact_multiple_of_batch(trained):
    spec, params, nt = trained
    ds = blobs_dataset(n=64, seed=6)
    out = ModelPredictor(spec, params, nt, batch_size=32).predict(ds)
    assert out["prediction"].shape == (64, 4)


def test_label_index_predictor_emits_classes(trained):
    spec, params, nt = trained
    # same seed as training: blob centers are seed-dependent
    ds = blobs_dataset(n=50, seed=0)
    out = LabelIndexPredictor(spec, params, nt, batch_size=16).predict(ds)
    assert out["prediction"].shape == (50,)
    assert out["prediction"].dtype == np.int32
    assert float(np.mean(out["prediction"] == ds["label"])) > 0.8


def test_accuracy_evaluator_score_matrix_vs_integer_labels():
    ds = Dataset({
        "prediction": np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]],
                               np.float32),
        "label": np.array([1, 0, 0], np.int32),
    })
    assert AccuracyEvaluator().evaluate(ds) == pytest.approx(2 / 3)


def test_accuracy_evaluator_onehot_labels():
    ds = Dataset({
        "prediction": np.array([[0.1, 0.9], [0.8, 0.2]], np.float32),
        "label": np.array([[0, 1], [0, 1]], np.float32),
    })
    assert AccuracyEvaluator().evaluate(ds) == pytest.approx(0.5)


def test_accuracy_evaluator_integer_predictions():
    ds = Dataset({
        "prediction": np.array([1, 0, 1, 1], np.int32),
        "label": np.array([1, 1, 1, 0], np.int32),
    })
    assert AccuracyEvaluator().evaluate(ds) == pytest.approx(0.5)


def test_accuracy_evaluator_binary_probability_column():
    ds = Dataset({
        "prediction": np.array([0.9, 0.2, 0.6], np.float32),
        "label": np.array([1, 0, 0], np.int32),
    })
    assert AccuracyEvaluator().evaluate(ds) == pytest.approx(2 / 3)


def test_loss_evaluator_mse():
    ds = Dataset({
        "prediction": np.array([1.0, 2.0], np.float32),
        "label": np.array([0.0, 2.0], np.float32),
    })
    assert LossEvaluator("mse").evaluate(ds) == pytest.approx(0.5)
