"""Inference-path coverage: ModelPredictor (incl. the pad-and-trim path),
LabelIndexPredictor, and the evaluators (SURVEY.md §3.4 parity surface)."""

import numpy as np
import pytest

from distkeras_tpu.data import Dataset
from distkeras_tpu.evaluators import AccuracyEvaluator, LossEvaluator
from distkeras_tpu.predictors import LabelIndexPredictor, ModelPredictor
from tests.test_trainers import blobs_dataset, model_spec


@pytest.fixture(scope="module")
def trained():
    """A spec + params good enough to beat chance on the blobs."""
    from distkeras_tpu import SingleTrainer

    ds = blobs_dataset(n=1024)
    t = SingleTrainer(model_spec(), loss="sparse_softmax_cross_entropy",
                      worker_optimizer="sgd", learning_rate=0.1,
                      batch_size=64, num_epoch=3)
    t.train(ds)
    return t.spec, t.trained_params_, t.trained_nt_


def test_predictor_pad_path_matches_direct_apply(trained):
    """n not divisible by batch_size: pad rows must be trimmed, predictions
    must equal a direct un-padded apply."""
    spec, params, nt = trained
    ds = blobs_dataset(n=70, seed=5)
    pred = ModelPredictor(spec, params, nt, batch_size=32)
    out = pred.predict(ds)
    assert out["prediction"].shape == (70, 4)
    direct, _ = spec.apply(params, nt, ds["features"], False)
    np.testing.assert_allclose(out["prediction"], np.asarray(direct),
                               rtol=1e-5, atol=1e-5)
    # original dataset untouched (with_column returns a new frame)
    assert "prediction" not in ds


def test_predictor_exact_multiple_of_batch(trained):
    spec, params, nt = trained
    ds = blobs_dataset(n=64, seed=6)
    out = ModelPredictor(spec, params, nt, batch_size=32).predict(ds)
    assert out["prediction"].shape == (64, 4)


def test_label_index_predictor_emits_classes(trained):
    spec, params, nt = trained
    # same seed as training: blob centers are seed-dependent
    ds = blobs_dataset(n=50, seed=0)
    out = LabelIndexPredictor(spec, params, nt, batch_size=16).predict(ds)
    assert out["prediction"].shape == (50,)
    assert out["prediction"].dtype == np.int32
    assert float(np.mean(out["prediction"] == ds["label"])) > 0.8


def test_accuracy_evaluator_score_matrix_vs_integer_labels():
    ds = Dataset({
        "prediction": np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]],
                               np.float32),
        "label": np.array([1, 0, 0], np.int32),
    })
    assert AccuracyEvaluator().evaluate(ds) == pytest.approx(2 / 3)


def test_accuracy_evaluator_onehot_labels():
    ds = Dataset({
        "prediction": np.array([[0.1, 0.9], [0.8, 0.2]], np.float32),
        "label": np.array([[0, 1], [0, 1]], np.float32),
    })
    assert AccuracyEvaluator().evaluate(ds) == pytest.approx(0.5)


def test_accuracy_evaluator_integer_predictions():
    ds = Dataset({
        "prediction": np.array([1, 0, 1, 1], np.int32),
        "label": np.array([1, 1, 1, 0], np.int32),
    })
    assert AccuracyEvaluator().evaluate(ds) == pytest.approx(0.5)


def test_accuracy_evaluator_binary_probability_column():
    ds = Dataset({
        "prediction": np.array([0.9, 0.2, 0.6], np.float32),
        "label": np.array([1, 0, 0], np.int32),
    })
    assert AccuracyEvaluator().evaluate(ds) == pytest.approx(2 / 3)


def test_loss_evaluator_mse():
    ds = Dataset({
        "prediction": np.array([1.0, 2.0], np.float32),
        "label": np.array([0.0, 2.0], np.float32),
    })
    assert LossEvaluator("mse").evaluate(ds) == pytest.approx(0.5)


def test_fscore_evaluator_binary_and_macro():
    from distkeras_tpu.evaluators import FScoreEvaluator

    # pred:  1 1 0 0 1 ; label: 1 0 0 1 1 → tp=2 fp=1 fn=1
    ds = Dataset({
        "prediction": np.array([1, 1, 0, 0, 1], np.int64),
        "label": np.array([1, 0, 0, 1, 1], np.int64),
    })
    assert FScoreEvaluator("precision").evaluate(ds) == pytest.approx(2 / 3)
    assert FScoreEvaluator("recall").evaluate(ds) == pytest.approx(2 / 3)
    assert FScoreEvaluator("f1").evaluate(ds) == pytest.approx(2 / 3)
    # class 0: tp=1 fp=1 fn=1 → p=r=f1=1/2; macro = (2/3 + 1/2) / 2
    assert FScoreEvaluator("f1", average="macro").evaluate(ds) == \
        pytest.approx((2 / 3 + 0.5) / 2)
    # score-matrix predictions argmax the same way AccuracyEvaluator does
    scores = np.zeros((5, 2), np.float32)
    scores[np.arange(5), [1, 1, 0, 0, 1]] = 1.0
    ds2 = Dataset({"prediction": scores, "label": ds["label"]})
    assert FScoreEvaluator("f1").evaluate(ds2) == pytest.approx(2 / 3)
    with pytest.raises(ValueError, match="metric"):
        FScoreEvaluator("jaccard")


def test_auc_evaluator():
    from distkeras_tpu.evaluators import AUCEvaluator

    # perfect ranking → AUC 1; anti-ranking → 0; random-ish hand case
    ds = Dataset({
        "prediction": np.array([0.9, 0.8, 0.2, 0.1], np.float32),
        "label": np.array([1, 1, 0, 0], np.int64),
    })
    assert AUCEvaluator().evaluate(ds) == pytest.approx(1.0)
    ds_rev = Dataset({
        "prediction": np.array([0.1, 0.2, 0.8, 0.9], np.float32),
        "label": np.array([1, 1, 0, 0], np.int64),
    })
    assert AUCEvaluator().evaluate(ds_rev) == pytest.approx(0.0)
    # one discordant pair of 4: AUC = 3/4; ties average to 0.5
    ds_mid = Dataset({
        "prediction": np.array([0.9, 0.3, 0.5, 0.1], np.float32),
        "label": np.array([1, 1, 0, 0], np.int64),
    })
    assert AUCEvaluator().evaluate(ds_mid) == pytest.approx(0.75)
    ds_tie = Dataset({
        "prediction": np.array([0.5, 0.5], np.float32),
        "label": np.array([1, 0], np.int64),
    })
    assert AUCEvaluator().evaluate(ds_tie) == pytest.approx(0.5)
    # [N, 2] score matrices use the positive column
    ds_mat = Dataset({
        "prediction": np.array([[0.1, 0.9], [0.8, 0.2]], np.float32),
        "label": np.array([1, 0], np.int64),
    })
    assert AUCEvaluator().evaluate(ds_mat) == pytest.approx(1.0)
    with pytest.raises(ValueError, match="both classes"):
        AUCEvaluator().evaluate(Dataset({
            "prediction": np.array([0.5, 0.6], np.float32),
            "label": np.array([1, 1], np.int64),
        }))


def test_auc_evaluator_pos_label_zero():
    """Regression: with [N, 2] score matrices the pos_label column must be
    used — a perfect class-0 classifier scores AUC 1, not 0."""
    from distkeras_tpu.evaluators import AUCEvaluator

    ds = Dataset({
        "prediction": np.array([[0.9, 0.1], [0.2, 0.8]], np.float32),
        "label": np.array([0, 1], np.int64),
    })
    assert AUCEvaluator(pos_label=0).evaluate(ds) == pytest.approx(1.0)
    assert AUCEvaluator(pos_label=1).evaluate(ds) == pytest.approx(1.0)


def test_auc_evaluator_pos_label_zero_single_column():
    """Regression (ADVICE r2): 1-D scores with pos_label=0 must negate the
    scores, so a perfect class-0 classifier scores 1.0, not 0.0."""
    from distkeras_tpu.evaluators import AUCEvaluator

    # high score = class 1; class-0 rows sit at the bottom — perfect for 0
    ds = Dataset({
        "prediction": np.array([0.1, 0.2, 0.8, 0.9], np.float32),
        "label": np.array([0, 0, 1, 1], np.int64),
    })
    assert AUCEvaluator(pos_label=0).evaluate(ds) == pytest.approx(1.0)
    assert AUCEvaluator(pos_label=1).evaluate(ds) == pytest.approx(1.0)
    with pytest.raises(ValueError, match="single score column"):
        AUCEvaluator(pos_label=2).evaluate(ds)


def test_fscore_macro_counts_predicted_only_classes():
    """Regression (ADVICE r2): macro averages over the union of label and
    prediction classes — a class predicted but absent from labels drags the
    macro down (sklearn semantics) instead of being skipped."""
    from distkeras_tpu.evaluators import FScoreEvaluator

    # class 2 never appears in labels but is predicted once: p=0, r=0, f1=0
    ds = Dataset({
        "prediction": np.array([1, 1, 0, 2], np.int64),
        "label": np.array([1, 1, 0, 0], np.int64),
    })
    # class 0: tp=1 fp=0 fn=1 → f1=2/3; class 1: tp=2 → f1=1; class 2: 0
    assert FScoreEvaluator("f1", average="macro").evaluate(ds) == \
        pytest.approx((2 / 3 + 1.0 + 0.0) / 3)


def test_auc_evaluator_multiclass_one_vs_rest():
    from distkeras_tpu.evaluators import AUCEvaluator

    # 3-class scores; class 2's score perfectly separates label==2
    scores = np.array([
        [0.5, 0.3, 0.9],
        [0.5, 0.3, 0.8],
        [0.5, 0.3, 0.2],
        [0.5, 0.3, 0.1],
    ], np.float32)
    labels = np.array([2, 2, 0, 1], np.int64)
    ds = Dataset({"prediction": scores, "label": labels})
    assert AUCEvaluator(pos_label=2).evaluate(ds) == pytest.approx(1.0)
    with pytest.raises(ValueError, match="out of range"):
        AUCEvaluator(pos_label=5).evaluate(ds)


def test_auc_evaluator_large_n_vectorized():
    from distkeras_tpu.evaluators import AUCEvaluator

    rng = np.random.default_rng(0)
    n = 200_000
    label = (rng.random(n) < 0.5).astype(np.int64)
    # noisy but informative scores, heavy ties via rounding
    score = np.round(label * 0.3 + rng.random(n), 2).astype(np.float32)
    ds = Dataset({"prediction": score, "label": label})
    import time
    t0 = time.perf_counter()
    auc = AUCEvaluator().evaluate(ds)
    dt = time.perf_counter() - t0
    assert 0.7 < auc < 0.9
    assert dt < 2.0, f"AUC took {dt:.2f}s for {n} rows"


def test_model_predictor_on_mesh_matches_single_device():
    """Mesh-sharded (data-parallel) inference: same predictions, rows
    sharded over dp, including the pad-and-trim path."""
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.models import mlp
    from distkeras_tpu.parallel.tensor import get_mesh_nd
    from distkeras_tpu.predictors import ModelPredictor

    assert len(jax.devices()) == 8
    mesh = get_mesh_nd({"dp": 8})
    spec = mlp(input_shape=(16,), hidden=(32,), num_classes=4,
               dtype=jnp.float32)
    params, nt = spec.init_np(0)
    rng = np.random.default_rng(0)
    # 37 rows: not divisible by batch 16 → exercises padding
    ds = Dataset({"features": rng.normal(size=(37, 16)).astype(np.float32)})

    single = ModelPredictor(spec, params, nt, batch_size=16).predict(ds)
    sharded = ModelPredictor(spec, params, nt, batch_size=16,
                             mesh=mesh).predict(ds)
    np.testing.assert_allclose(sharded["prediction"], single["prediction"],
                               rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError, match="not divisible"):
        ModelPredictor(spec, params, nt, batch_size=12, mesh=mesh)
    with pytest.raises(ValueError, match="not in mesh axes"):
        ModelPredictor(spec, params, nt, batch_size=16, mesh=mesh,
                       dp_axis="data")
