"""End-to-end trainer tests on the 8-fake-device CPU mesh (SURVEY.md §4)."""

import jax
import numpy as np
import pytest

from distkeras_tpu.data import Dataset
from distkeras_tpu.models import mlp
from distkeras_tpu.parallel.mesh import get_mesh
from distkeras_tpu.trainers import (
    ADAG,
    AEASGD,
    DOWNPOUR,
    DynSGD,
    EAMSGD,
    SingleTrainer,
)


def blobs_dataset(n=2048, dim=16, classes=4, seed=0):
    """Linearly separable Gaussian blobs — any trainer must fit these."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 3.0, size=(classes, dim)).astype(np.float32)
    labels = rng.integers(0, classes, size=n).astype(np.int32)
    x = centers[labels] + rng.normal(0, 1.0, size=(n, dim)).astype(np.float32)
    return Dataset.from_arrays(x, labels)


def model_spec(dim=16, classes=4):
    import jax.numpy as jnp
    return mlp(input_shape=(dim,), hidden=(32,), num_classes=classes,
               dtype=jnp.float32)


def final_loss(trainer):
    losses = trainer.get_history().losses()
    return float(np.mean([float(l) for l in losses[-3:]]))


def initial_loss(trainer):
    return float(trainer.get_history().losses()[0])


def test_single_trainer_learns():
    ds = blobs_dataset()
    t = SingleTrainer(model_spec(), loss="sparse_softmax_cross_entropy",
                      worker_optimizer="sgd", learning_rate=0.1,
                      batch_size=64, num_epoch=3)
    params = t.train(ds)
    assert params is not None
    assert final_loss(t) < 0.25
    assert final_loss(t) < initial_loss(t) / 3
    assert t.get_training_time() > 0
    assert len(t.get_history()) > 0


@pytest.mark.parametrize("cls,kw", [
    (ADAG, dict(communication_window=4)),
    (ADAG, dict(communication_window=1)),  # sync allreduce path
    (DOWNPOUR, dict(communication_window=4, learning_rate=0.02)),
    (AEASGD, dict(communication_window=8, learning_rate=0.05, rho=0.5)),
    (EAMSGD, dict(communication_window=8, learning_rate=0.05, rho=0.5,
                  momentum=0.8)),
    (DynSGD, dict(communication_window=4)),
])
def test_distributed_trainers_learn_on_8_device_mesh(cls, kw):
    assert len(jax.devices()) == 8, "conftest must provide 8 fake devices"
    ds = blobs_dataset(n=4096)
    kw.setdefault("learning_rate", 0.1)
    t = cls(model_spec(), loss="sparse_softmax_cross_entropy",
            worker_optimizer="sgd", num_workers=8, batch_size=32,
            num_epoch=3, **kw)
    t.train(ds, shuffle=True)
    assert final_loss(t) < 0.5, f"{cls.__name__} failed to learn: {final_loss(t)}"


def test_adag_one_worker_matches_single_trainer():
    """With W=1/window=1 the distributed path must equal the oracle exactly."""
    ds = blobs_dataset(n=512)
    mesh = get_mesh(1)
    common = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
                  learning_rate=0.05, batch_size=64, num_epoch=1, seed=7)
    t1 = SingleTrainer(model_spec(), mesh=mesh, **common)
    p1 = t1.train(ds)
    t2 = ADAG(model_spec(), num_workers=1, communication_window=1, mesh=mesh,
              **common)
    p2 = t2.train(ds)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        assert np.allclose(a, b, atol=1e-5)


def test_workers_actually_sharded_over_mesh():
    """The stacked worker axis must be split across all 8 devices."""
    from distkeras_tpu.parallel.local_sgd import LocalSGDEngine
    from distkeras_tpu.parallel.merge_rules import ADAGMerge
    import optax

    spec = model_spec()
    mesh = get_mesh(8)

    def loss_step(params, nt, batch):
        x, y = batch
        out, new_nt = spec.apply(params, nt, x, training=True)
        from distkeras_tpu.ops.losses import sparse_softmax_cross_entropy
        return sparse_softmax_cross_entropy(y, out), new_nt

    eng = LocalSGDEngine(spec, loss_step, optax.sgd(0.1), ADAGMerge(),
                         mesh, num_workers=8, window=2)
    params, nt = spec.init_np(0)
    state = eng.init_state(params, nt)
    leaf = jax.tree.leaves(state.workers)[0]
    assert len(leaf.sharding.device_set) == 8
    # center replicated
    cleaf = jax.tree.leaves(state.center)[0]
    assert cleaf.sharding.is_fully_replicated


def test_deterministic_across_runs():
    """Sync collective path is deterministic (SURVEY.md §5.2 build note)."""
    ds = blobs_dataset(n=1024)
    results = []
    for _ in range(2):
        t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                 num_workers=8, batch_size=16, communication_window=2,
                 learning_rate=0.05, num_epoch=1, seed=3)
        p = t.train(ds)
        results.append(jax.tree.leaves(p))
    for a, b in zip(*results):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_resident_vs_streaming_identical_single_worker():
    """W=1, no shuffle: the HBM-resident epoch path and the streaming
    per-window path must produce bit-identical results."""
    ds = blobs_dataset(n=512)
    mesh = get_mesh(1)
    common = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
                  learning_rate=0.05, batch_size=64, num_epoch=2, seed=11,
                  num_workers=1, communication_window=2, mesh=mesh)
    t_res = ADAG(model_spec(), device_data=True, **common)
    p_res = t_res.train(ds)
    t_str = ADAG(model_spec(), device_data=False, **common)
    p_str = t_str.train(ds)
    for a, b in zip(jax.tree.leaves(p_res), jax.tree.leaves(p_str)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # histories match too
    la = [round(float(x), 6) for x in t_res.get_history().losses()]
    lb = [round(float(x), 6) for x in t_str.get_history().losses()]
    assert la == lb


def test_resident_vs_streaming_identical_multi_worker():
    """W=8, no shuffle: worker_shards' interleave must match superbatches',
    so both data paths produce bit-identical training."""
    ds = blobs_dataset(n=2048)
    common = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
                  learning_rate=0.05, batch_size=16, num_epoch=2, seed=5,
                  num_workers=8, communication_window=2)
    p_res = ADAG(model_spec(), device_data=True, **common).train(ds)
    p_str = ADAG(model_spec(), device_data=False, **common).train(ds)
    for a, b in zip(jax.tree.leaves(p_res), jax.tree.leaves(p_str)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_streaming_mode_learns_on_mesh():
    ds = blobs_dataset(n=4096)
    t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="sgd", learning_rate=0.1, num_workers=8,
             batch_size=32, communication_window=4, num_epoch=3,
             device_data=False)
    t.train(ds, shuffle=True)
    assert final_loss(t) < 0.5


def test_resident_shuffle_changes_order_but_still_learns():
    ds = blobs_dataset(n=2048)
    t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="sgd", learning_rate=0.1, num_workers=8,
             batch_size=16, communication_window=2, num_epoch=3,
             device_data=True)
    t.train(ds, shuffle=True)
    assert final_loss(t) < 0.4


def test_resolve_optimizer_names():
    import optax

    from distkeras_tpu.trainers import resolve_optimizer

    for name in ("sgd", "adam", "adagrad", "rmsprop", "adadelta", "adamw",
                 "adamax", "nadam", "fused_adam"):
        tx = resolve_optimizer(name, 1e-3)
        assert isinstance(tx, optax.GradientTransformation), name
    # optax transforms pass through; unknown names raise
    assert resolve_optimizer(optax.sgd(0.1), 1e-3) is not None
    import pytest

    with pytest.raises(ValueError, match="unknown worker_optimizer"):
        resolve_optimizer("madgrad", 1e-3)


def test_learning_rate_accepts_optax_schedule():
    """The reference exposed Keras optimizer configs; here `learning_rate`
    may be an optax schedule (callable step -> lr) for any named optimizer —
    warmup/decay without custom optimizer objects."""
    import optax

    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=512)
    sched = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=0.05, warmup_steps=4, decay_steps=64)
    t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="sgd", learning_rate=sched, num_workers=4,
             batch_size=16, communication_window=2, num_epoch=3)
    t.train(ds, shuffle=True)
    losses = [float(l) for l in t.get_history().losses()]
    assert np.all(np.isfinite(losses))
    assert np.mean(losses[-3:]) < losses[0]


def test_gradient_clipping_kwargs():
    """Keras-optimizer parity: the reference's worker_optimizer was a Keras
    1.x optimizer carrying clipnorm/clipvalue. clipvalue clips elementwise;
    clipnorm clips by global norm (documented modern lowering)."""
    import jax.numpy as jnp
    import optax

    from distkeras_tpu.trainers import resolve_optimizer

    grads = {"w": jnp.array([3.0, -4.0])}  # global norm 5
    params = {"w": jnp.zeros(2)}

    tx = resolve_optimizer("sgd", 1.0, clipnorm=1.0)
    upd, _ = tx.update(grads, tx.init(params), params)
    np.testing.assert_allclose(  # scaled to norm 1, then sgd(-1x)
        np.asarray(upd["w"]), [-0.6, 0.8], rtol=1e-6)

    tx = resolve_optimizer("sgd", 1.0, clipvalue=0.5)
    upd, _ = tx.update(grads, tx.init(params), params)
    np.testing.assert_allclose(np.asarray(upd["w"]), [-0.5, 0.5], rtol=1e-6)

    # under the threshold both are the identity
    tx = resolve_optimizer("sgd", 1.0, clipnorm=100.0, clipvalue=100.0)
    upd, _ = tx.update(grads, tx.init(params), params)
    np.testing.assert_allclose(np.asarray(upd["w"]), [-3.0, 4.0], rtol=1e-6)

    # clipping chains in front of explicit optax transforms too
    tx = resolve_optimizer(optax.sgd(1.0), 1e-3, clipvalue=0.5)
    upd, _ = tx.update(grads, tx.init(params), params)
    np.testing.assert_allclose(np.asarray(upd["w"]), [-0.5, 0.5], rtol=1e-6)


def test_trainer_level_clipping_trains_and_caps_steps():
    """A SingleTrainer with a tiny clipnorm still learns, and the optimizer
    the trainer builds caps the global update norm at lr*clipnorm even for
    huge gradients."""
    import jax.numpy as jnp

    ds = blobs_dataset(n=512)
    t = SingleTrainer(model_spec(), loss="sparse_softmax_cross_entropy",
                      worker_optimizer="sgd", learning_rate=0.1,
                      batch_size=64, num_epoch=4, clipnorm=1.0)
    t.train(ds)
    losses = [float(l) for l in t.get_history().losses()]
    assert np.all(np.isfinite(losses))
    assert np.mean(losses[-3:]) < losses[0]
    # the magnitude bound, on the exact transform the trainer allocates
    tx = t.allocate_optimizer()
    grads = {"a": jnp.full((3,), 1e3), "b": jnp.full((2, 2), -1e3)}
    params = jax.tree.map(jnp.zeros_like, grads)
    upd, _ = tx.update(grads, tx.init(params), params)
    gnorm = float(jnp.sqrt(sum(jnp.sum(u * u) for u in jax.tree.leaves(upd))))
    np.testing.assert_allclose(gnorm, 0.1 * 1.0, rtol=1e-5)  # lr*clipnorm


def test_validation_data_per_epoch():
    """Keras-style validation_data: one val_loss/val_accuracy record per
    epoch, exact masked mean over real rows (pad rows excluded), and the
    numbers track training (val loss falls, accuracy rises on blobs)."""
    full = blobs_dataset(n=1325, seed=0)
    x, y = np.asarray(full["features"]), np.asarray(full["label"])
    ds = Dataset.from_arrays(x[:1024], y[:1024])
    val = Dataset.from_arrays(x[1024:], y[1024:])  # 301: not a batch multiple
    t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="adam", learning_rate=5e-3, num_workers=4,
             batch_size=32, communication_window=2, num_epoch=4,
             validation_data=val)
    t.train(ds, shuffle=True)
    recs = [r for r in t.get_history() if "val_loss" in r]
    assert len(recs) == 4
    assert [r["epoch"] for r in recs] == [0, 1, 2, 3]
    vls = t.get_history().val_losses()
    assert np.all(np.isfinite(vls))
    assert vls[-1] < vls[0]
    assert recs[-1]["val_accuracy"] > recs[0]["val_accuracy"] - 1e-9
    assert 0.0 <= recs[-1]["val_accuracy"] <= 1.0


def test_validation_loss_matches_manual_eval():
    """val_loss at the last epoch equals a hand-computed full-batch loss on
    the returned trained parameters."""
    import jax.numpy as jnp

    from distkeras_tpu.ops.losses import get_loss

    full = blobs_dataset(n=712, seed=0)
    x, y = np.asarray(full["features"]), np.asarray(full["label"])
    ds = Dataset.from_arrays(x[:512], y[:512])
    val = Dataset.from_arrays(x[512:], y[512:])
    spec = model_spec()
    t = SingleTrainer(spec, loss="sparse_softmax_cross_entropy",
                      worker_optimizer="sgd", learning_rate=0.05,
                      batch_size=64, num_epoch=2, validation_data=val)
    t.train(ds)
    rec = [r for r in t.get_history() if "val_loss" in r][-1]
    out, _ = spec.apply(t.trained_params_, t.trained_nt_,
                        jnp.asarray(val["features"]), training=False)
    manual = float(get_loss("sparse_softmax_cross_entropy")(
        jnp.asarray(val["label"]), out))
    np.testing.assert_allclose(rec["val_loss"], manual, rtol=1e-5)
    manual_acc = float(np.mean(
        np.argmax(np.asarray(out), -1) == np.asarray(val["label"])))
    np.testing.assert_allclose(rec["val_accuracy"], manual_acc, rtol=1e-6)


# -- Polyak/EMA averaging ----------------------------------------------------


def test_ps_ema_fold_matches_hand_computed():
    """PS-side EMA is exactly ema = d*ema + (1-d)*center after each fold."""
    from distkeras_tpu.parallel.merge_rules import DownpourMerge
    from distkeras_tpu.parameter_servers import ParameterServer

    d = 0.5
    ps = ParameterServer({"w": np.zeros(3, np.float32)}, DownpourMerge(),
                         num_workers=1, ema_decay=d)
    ema = np.zeros(3, np.float32)
    center = np.zeros(3, np.float32)
    rng = np.random.default_rng(0)
    for _ in range(5):
        delta = rng.normal(size=3).astype(np.float32)
        ps.commit(0, {"w": delta})
        center = center + delta            # DOWNPOUR fold
        ema = d * ema + (1 - d) * center
        np.testing.assert_allclose(ps.get_ema()["w"], ema, rtol=1e-6)
    np.testing.assert_allclose(ps.get_model()["w"], center, rtol=1e-6)


def test_collective_ema_decay_zero_equals_center():
    """decay=0 makes the EMA a copy of the latest center — pins the update
    order (EMA folds in the post-merge center each window)."""
    import jax

    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=512)
    t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="sgd", learning_rate=0.1, num_workers=4,
             batch_size=16, communication_window=2, num_epoch=2,
             device_data=False, ema_decay=0.0)
    params = t.train(ds, shuffle=True)
    assert t.ema_params_ is not None
    for la, lb in zip(jax.tree.leaves(t.ema_params_),
                      jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_collective_ema_tracks_behind_the_center():
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=512)
    t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="sgd", learning_rate=0.1, num_workers=4,
             batch_size=16, communication_window=2, num_epoch=2,
             device_data=False, ema_decay=0.9)
    params = t.train(ds, shuffle=True)
    import jax

    diffs = [float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
             for a, b in zip(jax.tree.leaves(t.ema_params_),
                             jax.tree.leaves(params))]
    assert max(diffs) > 0                       # it lags the raw center
    assert all(np.isfinite(d) for d in diffs)


def test_ema_forces_streaming_with_warning():
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=512)
    t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="sgd", learning_rate=0.1, num_workers=4,
             batch_size=16, communication_window=2, num_epoch=1,
             device_data=True, ema_decay=0.5)
    with pytest.warns(UserWarning, match="streaming"):
        t.train(ds)
    assert t.ema_params_ is not None


def test_ps_backend_ema_end_to_end():
    from distkeras_tpu import DOWNPOUR

    ds = blobs_dataset(n=1024)
    t = DOWNPOUR(model_spec(), loss="sparse_softmax_cross_entropy",
                 worker_optimizer="sgd", learning_rate=0.02, num_workers=2,
                 batch_size=32, communication_window=2, num_epoch=2,
                 backend="ps", ema_decay=0.9)
    t.train(ds, shuffle=True)
    assert t.ema_params_ is not None
    leaves = [np.asarray(l) for l in __import__("jax").tree.leaves(t.ema_params_)]
    assert all(np.isfinite(l).all() for l in leaves)


def test_ema_validation_errors():
    from distkeras_tpu import ADAG, DOWNPOUR

    with pytest.raises(ValueError, match="ema_decay must be"):
        ADAG(model_spec(), num_workers=2, ema_decay=1.0)
    # native transport supports EMA (C++ fold; tests/test_native_ps.py);
    # only an EXTERNAL server rejects it — its owner configures EMA
    with pytest.raises(ValueError, match="PS owner"):
        DOWNPOUR(model_spec(), num_workers=2, backend="ps",
                 ps_transport="socket", ps_host="127.0.0.1", ema_decay=0.9)
