"""Aux subsystems: checkpoint/resume, job deployment, parity aliases."""

import pathlib

import numpy as np
import pytest

from tests.test_trainers import blobs_dataset, model_spec

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_checkpoint_roundtrip(tmp_path):
    from distkeras_tpu import checkpoint as ckpt

    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "nested": {"b": np.ones(4, np.int32)}}
    ckpt.save_checkpoint(tmp_path, tree, step=3)
    ckpt.save_checkpoint(tmp_path, {"a": tree["a"] * 2,
                                    "nested": tree["nested"]}, step=7)
    assert ckpt.latest_step(tmp_path) == 7
    restored, step = ckpt.restore_checkpoint(tmp_path)
    assert step == 7
    assert np.allclose(restored["a"], tree["a"] * 2)
    old, _ = ckpt.restore_checkpoint(tmp_path, step=3)
    assert np.allclose(old["a"], tree["a"])


def test_checkpoint_keep_prunes(tmp_path):
    from distkeras_tpu import checkpoint as ckpt

    for s in range(6):
        ckpt.save_checkpoint(tmp_path, {"x": np.zeros(1)}, step=s, keep=2)
    steps = sorted(
        int(p.name[5:-4]) for p in tmp_path.glob("ckpt_*.dkc")
    )
    assert steps == [4, 5]


def test_trainer_resume_continues(tmp_path):
    """Train 2 epochs w/ checkpointing == train 1, resume, train 1 more."""
    import jax
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=512)
    common = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
                  learning_rate=0.05, num_workers=4, batch_size=16,
                  communication_window=2, seed=9)

    full = ADAG(model_spec(), num_epoch=2, **common)
    p_full = full.train(ds)

    d = tmp_path / "ck"
    t1 = ADAG(model_spec(), num_epoch=1, checkpoint_dir=d, **common)
    t1.train(ds)
    t2 = ADAG(model_spec(), num_epoch=2, checkpoint_dir=d, resume=True,
              **common)
    p_resumed = t2.train(ds)

    for a, b in zip(jax.tree.leaves(p_full), jax.tree.leaves(p_resumed)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # resumed run only trained the second epoch
    epochs = {r.get("epoch") for r in t2.get_history()}
    assert epochs == {1}


def test_ps_backend_resume_continues(tmp_path):
    """PS backend: train 2 epochs w/ checkpointing == train 1, resume, +1.

    W=1 keeps the hogwild path deterministic; adam exercises optimizer-state
    restoration (plain SGD would pass even if opt state were dropped).
    """
    import jax
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=512)
    common = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="adam",
                  learning_rate=2e-3, num_workers=1, batch_size=16,
                  communication_window=2, backend="ps", seed=9)

    full = ADAG(model_spec(), num_epoch=2, **common)
    p_full = full.train(ds)

    d = tmp_path / "ck"
    t1 = ADAG(model_spec(), num_epoch=1, checkpoint_dir=d, **common)
    t1.train(ds)
    assert list(d.glob("ckpt_*.dkc")), "PS backend wrote no checkpoints"
    t2 = ADAG(model_spec(), num_epoch=2, checkpoint_dir=d, resume=True,
              **common)
    p_resumed = t2.train(ds)

    for a, b in zip(jax.tree.leaves(p_full), jax.tree.leaves(p_resumed)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    epochs = {r.get("epoch") for r in t2.get_history()}
    assert epochs == {1}


def test_ps_backend_resume_multiworker_smoke(tmp_path):
    """W=4 hogwild: checkpoints are written at epoch barriers and a resumed
    run trains only the remaining epochs (bit-equality is not defined for
    hogwild — commit interleaving is nondeterministic by design)."""
    from distkeras_tpu import DOWNPOUR

    ds = blobs_dataset(n=1024)
    common = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
                  learning_rate=0.02, num_workers=4, batch_size=16,
                  communication_window=2, backend="ps", seed=3)
    d = tmp_path / "ck"
    t1 = DOWNPOUR(model_spec(), num_epoch=2, checkpoint_dir=d, **common)
    t1.train(ds)
    steps = sorted(int(p.name[5:-4]) for p in d.glob("ckpt_*.dkc"))
    assert steps == [0, 1]
    t2 = DOWNPOUR(model_spec(), num_epoch=3, checkpoint_dir=d, resume=True,
                  **common)
    t2.train(ds)
    assert {r.get("epoch") for r in t2.get_history()} == {2}
    losses = [float(l) for l in t2.get_history().losses()]
    assert np.all(np.isfinite(losses))


def test_ps_backend_resume_worker_count_mismatch_goes_elastic(tmp_path):
    """A worker-count mismatch on PS resume is no longer fatal: it warns and
    resumes elastically from the center (exact-resume state dropped)."""
    from distkeras_tpu import DOWNPOUR

    ds = blobs_dataset(n=512)
    common = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
                  learning_rate=0.02, batch_size=16, communication_window=2,
                  backend="ps", seed=3)
    d = tmp_path / "ck"
    DOWNPOUR(model_spec(), num_epoch=1, num_workers=2, checkpoint_dir=d,
             **common).train(ds)
    t = DOWNPOUR(model_spec(), num_epoch=2, num_workers=4, checkpoint_dir=d,
                 resume=True, **common)
    with pytest.warns(UserWarning, match="elastic resume"):
        t.train(ds)
    hist = [r for r in t.get_history() if "loss" in r]
    assert {r["epoch"] for r in hist} == {1}


def test_profiler_and_metrics_stream(tmp_path, capsys):
    """profile_dir writes a jax.profiler trace; log_metrics streams per-epoch
    JSONL with samples/sec + updates/sec (SURVEY.md §5.1/§5.5 build notes)."""
    import json
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=512)
    prof = tmp_path / "prof"
    t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="sgd", learning_rate=0.05, num_workers=4,
             batch_size=16, communication_window=2, num_epoch=2,
             profile_dir=prof, log_metrics=True)
    t.train(ds)
    # profiler artifacts exist
    assert any(prof.rglob("*")), "profile_dir is empty"
    # one JSON metrics line per epoch on stdout
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    epoch_lines = [l for l in lines if l.get("metric") == "epoch"]
    assert len(epoch_lines) == 2
    assert epoch_lines[0]["samples_per_sec"] > 0
    assert epoch_lines[0]["updates_per_sec"] > 0
    # and the same metrics live in the history / metrics_
    assert len(t.metrics_) == 2
    assert any("samples_per_sec" in r for r in t.get_history())


def test_initialize_cluster_kwargs_plumbing(monkeypatch):
    """initialize_cluster must forward exactly the provided kwargs to
    jax.distributed.initialize and report the global topology."""
    import jax
    from distkeras_tpu import job_deployment as jd

    seen = {}
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: seen.update(kw))
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    info = jd.initialize_cluster("coord:9999", num_processes=2, process_id=1,
                                 local_device_ids=[0, 1])
    assert seen == {"coordinator_address": "coord:9999", "num_processes": 2,
                    "process_id": 1, "local_device_ids": [0, 1]}
    assert info["process_index"] == 1 and info["process_count"] == 2
    assert info["global_devices"] == 8  # the fake CPU mesh

    # no-arg TPU-pod form: nothing forwarded
    seen.clear()
    jd.initialize_cluster()
    assert seen == {}


def test_job_renders_per_host_commands():
    from distkeras_tpu.job_deployment import Job, Punchcard

    pc = Punchcard(script="train.py", hosts=["tpu-a", "tpu-b"],
                   args=["--epochs", "3"], env={"FOO": "1"})
    cmds = Job(pc).run()
    assert len(cmds) == 2
    host0, cmd0 = cmds[0]
    assert host0 == "tpu-a"
    assert "DISTKERAS_COORDINATOR=tpu-a:8476" in cmd0
    assert "DISTKERAS_PROCESS_ID=0" in cmd0
    assert "train.py --epochs 3" in cmd0
    _, cmd1 = cmds[1]
    assert "DISTKERAS_PROCESS_ID=1" in cmd1


def test_ssh_runner_renders_and_fans_out():
    """SSHRunner (VERDICT r4 #6: the reference Job's remote-submission
    seam): a 2-host punchcard fans out one ssh client argv per host, with
    the coordinator env + script inside the single remote-command
    argument. Fake transport — no real SSH in this environment."""
    from distkeras_tpu.job_deployment import Job, Punchcard, SSHRunner

    calls = []
    runner = SSHRunner(user="ops", port=2222, identity_file="/k/id",
                       ssh_options=["-o", "StrictHostKeyChecking=no"],
                       transport=calls.append)
    pc = Punchcard(script="train.py", hosts=["tpu-a", "tpu-b"],
                   args=["--epochs", "3"], env={"FOO": "1"})
    cmds = Job(pc, runner=runner).run()
    assert len(calls) == len(cmds) == 2
    argv0, argv1 = calls
    assert argv0[0] == "ssh"
    assert ["-o", "BatchMode=yes"] == argv0[1:3]
    assert ["-p", "2222"] in (argv0[i:i + 2] for i in range(len(argv0)))
    assert ["-i", "/k/id"] in (argv0[i:i + 2] for i in range(len(argv0)))
    assert "StrictHostKeyChecking=no" in argv0
    # target and remote command are the final two arguments
    assert argv0[-2] == "ops@tpu-a" and argv1[-2] == "ops@tpu-b"
    remote0, remote1 = argv0[-1], argv1[-1]
    assert "DISTKERAS_COORDINATOR=tpu-a:8476" in remote0
    assert "DISTKERAS_NUM_PROCESSES=2" in remote0
    assert "DISTKERAS_PROCESS_ID=0" in remote0
    assert "DISTKERAS_PROCESS_ID=1" in remote1
    assert "FOO=1" in remote0
    assert "train.py --epochs 3" in remote0
    # the rendered remote command is EXACTLY what LocalRunner would run
    assert [c for _, c in cmds] == [remote0, remote1]
    assert runner.launched[0][0] == "tpu-a"


def test_ssh_runner_validates_hosts_before_launch():
    """A bad host anywhere in the list must fail BEFORE any launch (a
    mid-launch rejection would leak cluster processes blocking in
    jax.distributed.initialize)."""
    import pytest

    from distkeras_tpu.job_deployment import Job, Punchcard, SSHRunner

    calls = []
    runner = SSHRunner(transport=calls.append)
    pc = Punchcard(script="t.py", hosts=["good-host", "-oProxyCommand=x"])
    with pytest.raises(ValueError, match="option"):
        Job(pc, runner=runner).run()
    assert calls == []  # nothing launched
    with pytest.raises(ValueError, match="invalid ssh host"):
        SSHRunner(transport=calls.append).validate("bad host")


def test_ssh_runner_default_argv_minimal():
    """No user/port/identity → bare `ssh -o BatchMode… host cmd` (and the
    default transport would Popen this argv; not executed here)."""
    from distkeras_tpu.job_deployment import SSHRunner

    argv = SSHRunner().ssh_argv("node1", "echo hi")
    assert argv[0] == "ssh" and argv[-2:] == ["node1", "echo hi"]
    assert "-p" not in argv and "-i" not in argv


def test_punchcard_save_load(tmp_path):
    from distkeras_tpu.job_deployment import Punchcard

    pc = Punchcard(script="x.py", hosts=["h1"], coordinator_port=9000)
    path = tmp_path / "job.json"
    pc.save(path)
    back = Punchcard.load(path)
    assert back.script == "x.py" and back.coordinator_port == 9000


def test_cluster_args_from_env(monkeypatch):
    from distkeras_tpu.job_deployment import cluster_args_from_env

    monkeypatch.setenv("DISTKERAS_COORDINATOR", "h:1234")
    monkeypatch.setenv("DISTKERAS_NUM_PROCESSES", "4")
    monkeypatch.setenv("DISTKERAS_PROCESS_ID", "2")
    args = cluster_args_from_env()
    assert args == {"coordinator_address": "h:1234", "num_processes": 4,
                    "process_id": 2}


def test_asynchronous_distributed_trainer_alias():
    import distkeras_tpu.trainers as tr

    assert issubclass(tr.ADAG, tr.AsynchronousDistributedTrainer)
    assert issubclass(tr.EAMSGD, tr.AsynchronousDistributedTrainer)
    assert issubclass(tr.AsynchronousDistributedTrainer, tr.DistributedTrainer)


def test_utils_parity_helpers():
    from distkeras_tpu import utils
    from distkeras_tpu.data import Dataset

    ds = Dataset({"x": np.arange(10)})
    assert len(utils.shuffle(ds)) == 10
    row = utils.new_dataframe_row({"a": 1}, "b", 2)
    assert row == {"a": 1, "b": 2}
    assert np.array_equal(utils.to_vector(2, 4), [0, 0, 1, 0])
    assert np.array_equal(
        utils.to_dense_vector([1.0, 2.0], [0, 3], 4), [1, 0, 0, 2]
    )


def test_trainer_elastic_resume_changes_worker_count(tmp_path):
    """A checkpoint written at W=4 resumes at W=8: the center carries over
    (worker state re-broadcast), the step counter survives, and training
    continues to improve."""
    import jax
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=512)
    common = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
                  learning_rate=0.05, batch_size=16,
                  communication_window=2, seed=9)

    d = tmp_path / "ck"
    t1 = ADAG(model_spec(), num_epoch=2, num_workers=4, checkpoint_dir=d,
              **common)
    t1.train(ds)
    loss_before = [r["loss"] for r in t1.get_history() if "loss" in r][-1]

    t2 = ADAG(model_spec(), num_epoch=4, num_workers=8, checkpoint_dir=d,
              resume=True, **common)
    with pytest.warns(UserWarning, match="elastic resume"):
        p = t2.train(ds)
    hist = [r for r in t2.get_history() if "loss" in r]
    losses = [r["loss"] for r in hist]
    assert np.all(np.isfinite(losses))
    # only epochs 2..3 were trained (epochs 0..1 came from the checkpoint)
    assert {r.get("epoch") for r in hist} == {2, 3}
    # resumed from the trained center, not from scratch: the first resumed
    # loss is already near the pre-resume loss, far below a fresh model's
    fresh = ADAG(model_spec(), num_epoch=1, num_workers=8, **common)
    fresh.train(ds)
    fresh_first = [r["loss"] for r in fresh.get_history() if "loss" in r][0]
    assert losses[0] < 0.5 * fresh_first
    assert losses[-1] <= loss_before * 1.5  # keeps training sanely
    assert jax.tree.leaves(p)[0] is not None


@pytest.mark.slow  # 2-process jax.distributed cluster; command-render pin stays fast
def test_job_local_runner_launches_real_cluster(tmp_path):
    """End-to-end launch: Punchcard → Job → LocalRunner actually starts a
    2-process `jax.distributed` cluster on localhost; both processes see
    process_count=2 and agree on a cross-process allgather."""
    import json
    import socket
    import textwrap

    from distkeras_tpu.job_deployment import Job, LocalRunner, Punchcard

    with socket.socket() as s:  # free coordinator port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(f"""
        import json, sys
        sys.path.insert(0, {str(REPO)!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        from distkeras_tpu.job_deployment import (
            cluster_args_from_env, initialize_cluster)
        info = initialize_cluster(**cluster_args_from_env())
        from jax.experimental import multihost_utils
        import jax.numpy as jnp
        got = multihost_utils.process_allgather(
            jnp.array([jax.process_index() + 1]))
        out = {{"info": info, "allgather": got.ravel().tolist()}}
        with open({str(tmp_path)!r} + f"/out_{{jax.process_index()}}.json",
                  "w") as f:
            json.dump(out, f)
    """))

    pc = Punchcard(script=str(worker), hosts=["localhost", "localhost"],
                   coordinator_port=port)
    runner = LocalRunner()
    job = Job(pc, runner=runner)
    cmds = job.run()
    assert len(cmds) == 2
    codes = runner.wait(timeout=240)
    assert codes == [0, 0], [p.captured_stderr[-500:] for p in runner.procs]
    for i in range(2):
        rec = json.loads((tmp_path / f"out_{i}.json").read_text())
        assert rec["info"]["process_count"] == 2
        # each process contributes its local devices (8 virtual CPUs under
        # the CI flags) to the global view
        assert rec["info"]["global_devices"] == \
            2 * rec["info"]["local_devices"]
        assert sorted(rec["allgather"]) == [1, 2]
    # non-local hosts are refused — and a mixed host list is rejected
    # BEFORE anything launches (no leaked half-cluster)
    with pytest.raises(ValueError, match="localhost"):
        LocalRunner()("tpu-host-7", "echo hi")
    bad = Punchcard(script=str(worker), hosts=["localhost", "tpu-host-7"],
                    coordinator_port=port)
    r2 = LocalRunner()
    with pytest.raises(ValueError, match="localhost"):
        Job(bad, runner=r2).run()
    assert r2.procs == []


def test_sharded_checkpoint_roundtrip_single_process(tmp_path):
    """The process-sharded checkpoint format: leaves sharded over the
    8-device mesh are written as per-shard regions and reassembled exactly;
    latest_step/restore_checkpoint dispatch across both formats."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distkeras_tpu import checkpoint as ckpt
    from distkeras_tpu.parallel.mesh import get_mesh

    mesh = get_mesh(8)
    axis = mesh.axis_names[0]
    rng = np.random.default_rng(0)
    sharded = jax.device_put(
        rng.normal(size=(16, 4)).astype(np.float32),
        NamedSharding(mesh, P(axis, None)),
    )
    replicated = jax.device_put(
        rng.normal(size=(3, 3)).astype(np.float32),
        NamedSharding(mesh, P()),
    )
    tree = {"s": sharded, "r": replicated, "step": 7, "host": np.arange(5)}
    ckpt._save_sharded(tmp_path, tree, step=3)
    assert ckpt.latest_step(tmp_path) == 3
    got, step = ckpt.restore_checkpoint(tmp_path)
    assert step == 3
    np.testing.assert_array_equal(got["s"], np.asarray(sharded))
    np.testing.assert_array_equal(got["r"], np.asarray(replicated))
    assert int(got["step"]) == 7
    np.testing.assert_array_equal(got["host"], np.arange(5))

    # newer plain checkpoint wins the latest_step race; both restorable
    ckpt.save_checkpoint(tmp_path, {"x": np.ones(2)}, step=5)
    assert ckpt.latest_step(tmp_path) == 5
    got5, _ = ckpt.restore_checkpoint(tmp_path, step=5)
    np.testing.assert_array_equal(got5["x"], np.ones(2))
    got3, _ = ckpt.restore_checkpoint(tmp_path, step=3)
    np.testing.assert_array_equal(got3["s"], np.asarray(sharded))


def test_sharded_checkpoint_detects_missing_shard(tmp_path):
    """A sharded snapshot with a missing region fails loudly, not with
    silently-zero weights."""
    import pickle

    from distkeras_tpu import checkpoint as ckpt

    tree = {"w": np.arange(8, dtype=np.float32)}
    ckpt._save_sharded(tmp_path, tree, step=0)
    shard_file = ckpt._shard_file(tmp_path, 0, 0, 1)
    payload = pickle.loads(shard_file.read_bytes())
    # drop the region covering the leaf
    payload["shards"] = {}
    shard_file.write_bytes(pickle.dumps(payload))
    with pytest.raises(ValueError, match="cover"):
        ckpt.restore_checkpoint(tmp_path, step=0)


def test_checkpoint_cross_format_step_collision(tmp_path):
    """Both formats at one step (directory reused across a topology
    change): the newer write wins restore, and pruning removes old steps
    of BOTH formats."""
    import time as _time

    from distkeras_tpu import checkpoint as ckpt

    ckpt.save_checkpoint(tmp_path, {"w": np.zeros(4)}, step=3)
    _time.sleep(0.05)  # distinct mtimes
    ckpt._save_sharded(tmp_path, {"w": np.ones(4)}, step=3)
    got, _ = ckpt.restore_checkpoint(tmp_path, step=3)
    np.testing.assert_array_equal(got["w"], np.ones(4))  # sharded is newer

    # old plain steps are pruned by the sharded writer too (keep=3)
    for s in (0, 1):
        ckpt.save_checkpoint(tmp_path, {"w": np.zeros(1)}, step=s)
    for s in (4, 5, 6):
        ckpt._save_sharded(tmp_path, {"w": np.ones(1)}, step=s)
    remaining = {st for st, _ in ckpt._all_checkpoint_files(tmp_path)}
    assert remaining == {4, 5, 6}


def test_checkpoint_rollback_save_not_pruned(tmp_path):
    """A run resumed from a rollback saves a LOWER step than stale future
    checkpoints; its fresh save must survive (and win) pruning."""
    from distkeras_tpu import checkpoint as ckpt

    for s in (150, 151, 152):
        ckpt.save_checkpoint(tmp_path, {"w": np.zeros(1)}, step=s)
    path = ckpt.save_checkpoint(tmp_path, {"w": np.ones(1)}, step=101)
    assert path.exists()
    got, _ = ckpt.restore_checkpoint(tmp_path, step=101)
    np.testing.assert_array_equal(got["w"], np.ones(1))


def test_checkpoint_rollback_truncates_abandoned_future(tmp_path):
    """Saving a LOWER step declares a new timeline: higher (abandoned)
    steps are truncated, so latest_step tracks the live run and the keep
    budget isn't eaten by dead checkpoints."""
    from distkeras_tpu import checkpoint as ckpt

    for s in (150, 151, 152):
        ckpt.save_checkpoint(tmp_path, {"w": np.zeros(1)}, step=s)
    ckpt.save_checkpoint(tmp_path, {"w": np.ones(1)}, step=101)
    assert ckpt.latest_step(tmp_path) == 101        # not the dead 152
    for s in (102, 103):
        ckpt.save_checkpoint(tmp_path, {"w": np.ones(1) * s}, step=s)
    remaining = {st for st, _ in ckpt._all_checkpoint_files(tmp_path)}
    assert remaining == {101, 102, 103}
    got, _ = ckpt.restore_checkpoint(tmp_path)
    np.testing.assert_array_equal(got["w"], np.ones(1) * 103)


def test_async_checkpoint_resume_equals_sync(tmp_path):
    """checkpoint_async=True writes on a background thread; the resulting
    checkpoints resume identically to synchronous ones (jax arrays are
    immutable, so the in-flight snapshot stays consistent while the next
    epoch trains)."""
    import jax
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=512)
    common = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
                  learning_rate=0.05, num_workers=4, batch_size=16,
                  communication_window=2, seed=9)

    full = ADAG(model_spec(), num_epoch=2, **common)
    p_full = full.train(ds)

    d = tmp_path / "ck"
    t1 = ADAG(model_spec(), num_epoch=1, checkpoint_dir=d,
              checkpoint_async=True, **common)
    t1.train(ds)  # train() joins the in-flight save before returning
    from distkeras_tpu import checkpoint as ckpt

    assert ckpt.latest_step(d) == 0
    t2 = ADAG(model_spec(), num_epoch=2, checkpoint_dir=d, resume=True,
              checkpoint_async=True, **common)
    p_resumed = t2.train(ds)
    for a, b in zip(jax.tree.leaves(p_full), jax.tree.leaves(p_resumed)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_async_checkpoint_mesh_trainer(tmp_path):
    """MeshTrainer async checkpoints: FSDP resume equality, async vs sync."""
    import jax
    import jax.numpy as jnp
    from distkeras_tpu.models import mlp
    from distkeras_tpu.trainers import MeshTrainer

    from tests.test_trainers import blobs_dataset

    ds = blobs_dataset(n=256)
    common = dict(loss="sparse_softmax_cross_entropy",
                  worker_optimizer="adam", learning_rate=1e-3,
                  mesh_shape={"dp": 8}, parameter_sharding="fsdp",
                  batch_size=32, seed=5, input_mode="stream")
    spec = lambda: mlp(input_shape=(16,), hidden=(32,), num_classes=3,
                       dtype=jnp.float32)
    p_full = MeshTrainer(spec(), num_epoch=2, **common).train(ds)

    d = tmp_path / "ck"
    MeshTrainer(spec(), num_epoch=1, checkpoint_dir=d,
                checkpoint_async=True, **common).train(ds)
    p_res = MeshTrainer(spec(), num_epoch=2, checkpoint_dir=d, resume=True,
                        checkpoint_async=True, **common).train(ds)
    for a, b in zip(jax.tree.leaves(p_full), jax.tree.leaves(p_res)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_async_checkpoint_error_surfaces(tmp_path):
    """A failing background save must raise (at the next boundary or at
    train end), never pass silently."""
    from distkeras_tpu import checkpoint as ckpt

    ac = ckpt.AsyncCheckpointer()
    target = tmp_path / "not_a_dir"
    target.write_text("file, not directory")  # mkdir(parents=True) fails
    ac.save(target / "sub", {"w": np.ones(2)}, step=0)
    with pytest.raises((OSError, FileExistsError, NotADirectoryError)):
        ac.wait()
    # a later successful save still works on the same checkpointer
    ac.save(tmp_path / "ok", {"w": np.ones(2)}, step=1)
    ac.wait()
    assert ckpt.latest_step(tmp_path / "ok") == 1


def test_async_checkpoint_rejected_on_ps_backend():
    from distkeras_tpu import DOWNPOUR

    ds = blobs_dataset(n=256)
    t = DOWNPOUR(model_spec(), loss="sparse_softmax_cross_entropy",
                 worker_optimizer="sgd", learning_rate=0.02, num_workers=2,
                 batch_size=16, communication_window=2, backend="ps",
                 checkpoint_dir="/tmp/nope", checkpoint_async=True)
    with pytest.raises(ValueError, match="checkpoint_async"):
        t.train(ds)
