"""Aux subsystems: checkpoint/resume, job deployment, parity aliases."""

import numpy as np
import pytest

from tests.test_trainers import blobs_dataset, model_spec


def test_checkpoint_roundtrip(tmp_path):
    from distkeras_tpu import checkpoint as ckpt

    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "nested": {"b": np.ones(4, np.int32)}}
    ckpt.save_checkpoint(tmp_path, tree, step=3)
    ckpt.save_checkpoint(tmp_path, {"a": tree["a"] * 2,
                                    "nested": tree["nested"]}, step=7)
    assert ckpt.latest_step(tmp_path) == 7
    restored, step = ckpt.restore_checkpoint(tmp_path)
    assert step == 7
    assert np.allclose(restored["a"], tree["a"] * 2)
    old, _ = ckpt.restore_checkpoint(tmp_path, step=3)
    assert np.allclose(old["a"], tree["a"])


def test_checkpoint_keep_prunes(tmp_path):
    from distkeras_tpu import checkpoint as ckpt

    for s in range(6):
        ckpt.save_checkpoint(tmp_path, {"x": np.zeros(1)}, step=s, keep=2)
    steps = sorted(
        int(p.name[5:-4]) for p in tmp_path.glob("ckpt_*.dkc")
    )
    assert steps == [4, 5]


def test_trainer_resume_continues(tmp_path):
    """Train 2 epochs w/ checkpointing == train 1, resume, train 1 more."""
    import jax
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=512)
    common = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
                  learning_rate=0.05, num_workers=4, batch_size=16,
                  communication_window=2, seed=9)

    full = ADAG(model_spec(), num_epoch=2, **common)
    p_full = full.train(ds)

    d = tmp_path / "ck"
    t1 = ADAG(model_spec(), num_epoch=1, checkpoint_dir=d, **common)
    t1.train(ds)
    t2 = ADAG(model_spec(), num_epoch=2, checkpoint_dir=d, resume=True,
              **common)
    p_resumed = t2.train(ds)

    for a, b in zip(jax.tree.leaves(p_full), jax.tree.leaves(p_resumed)):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # resumed run only trained the second epoch
    epochs = {r.get("epoch") for r in t2.get_history()}
    assert epochs == {1}


def test_job_renders_per_host_commands():
    from distkeras_tpu.job_deployment import Job, Punchcard

    pc = Punchcard(script="train.py", hosts=["tpu-a", "tpu-b"],
                   args=["--epochs", "3"], env={"FOO": "1"})
    cmds = Job(pc).run()
    assert len(cmds) == 2
    host0, cmd0 = cmds[0]
    assert host0 == "tpu-a"
    assert "DISTKERAS_COORDINATOR=tpu-a:8476" in cmd0
    assert "DISTKERAS_PROCESS_ID=0" in cmd0
    assert "train.py --epochs 3" in cmd0
    _, cmd1 = cmds[1]
    assert "DISTKERAS_PROCESS_ID=1" in cmd1


def test_punchcard_save_load(tmp_path):
    from distkeras_tpu.job_deployment import Punchcard

    pc = Punchcard(script="x.py", hosts=["h1"], coordinator_port=9000)
    path = tmp_path / "job.json"
    pc.save(path)
    back = Punchcard.load(path)
    assert back.script == "x.py" and back.coordinator_port == 9000


def test_cluster_args_from_env(monkeypatch):
    from distkeras_tpu.job_deployment import cluster_args_from_env

    monkeypatch.setenv("DISTKERAS_COORDINATOR", "h:1234")
    monkeypatch.setenv("DISTKERAS_NUM_PROCESSES", "4")
    monkeypatch.setenv("DISTKERAS_PROCESS_ID", "2")
    args = cluster_args_from_env()
    assert args == {"coordinator_address": "h:1234", "num_processes": 4,
                    "process_id": 2}


def test_asynchronous_distributed_trainer_alias():
    import distkeras_tpu.trainers as tr

    assert issubclass(tr.ADAG, tr.AsynchronousDistributedTrainer)
    assert issubclass(tr.EAMSGD, tr.AsynchronousDistributedTrainer)
    assert issubclass(tr.AsynchronousDistributedTrainer, tr.DistributedTrainer)


def test_utils_parity_helpers():
    from distkeras_tpu import utils
    from distkeras_tpu.data import Dataset

    ds = Dataset({"x": np.arange(10)})
    assert len(utils.shuffle(ds)) == 10
    row = utils.new_dataframe_row({"a": 1}, "b", 2)
    assert row == {"a": 1, "b": 2}
    assert np.array_equal(utils.to_vector(2, 4), [0, 0, 1, 0])
    assert np.array_equal(
        utils.to_dense_vector([1.0, 2.0], [0, 3], 4), [1, 0, 0, 2]
    )
