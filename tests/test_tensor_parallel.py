"""Tensor parallelism (Megatron GSPMD) vs the single-device oracle.

The dp×tp SPMD train step must compute EXACTLY the single-device math — the
sharding annotations change layout and collectives, never values — so every
test here is an equality test against a plain local step on the same data.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from distkeras_tpu.models import transformer_classifier
from distkeras_tpu.ops.losses import sparse_softmax_cross_entropy
from distkeras_tpu.parallel.tensor import (
    SPMDEngine,
    assert_param_shardings,
    get_mesh_nd,
    megatron_specs,
    shard_pytree,
)

DIM, HEADS, DEPTH, VOCAB, MAXLEN, CLASSES = 32, 4, 2, 64, 16, 4


def small_spec():
    return transformer_classifier(
        vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS, depth=DEPTH,
        num_classes=CLASSES, dtype=jnp.float32,
    )


def batch(rng, B=8):
    toks = rng.integers(0, VOCAB, size=(B, MAXLEN)).astype(np.int32)
    mask = np.ones((B, MAXLEN), np.float32)
    mask[:, MAXLEN - 4:] = 0.0  # padded tail exercises the key mask
    y = rng.integers(0, CLASSES, size=(B,)).astype(np.int32)
    return toks, mask, y


def loss_step(spec):
    def fn(params, nt, b):
        toks, mask, y = b
        out, new_nt = spec.apply(params, nt, (toks, mask), training=True)
        return sparse_softmax_cross_entropy(y, out), new_nt

    return fn


def test_megatron_specs_layout():
    spec = small_spec()
    params, _ = spec.init_np(0)
    specs = megatron_specs(params)
    blk = specs["blocks_0"]
    assert blk["qkv"]["kernel"] == P(None, "tp")
    assert blk["qkv"]["bias"] == P("tp")
    assert blk["mlp_up"]["kernel"] == P(None, "tp")
    assert blk["attn_out"]["kernel"] == P("tp", None)
    assert blk["attn_out"]["bias"] == P()
    assert blk["mlp_down"]["kernel"] == P("tp", None)
    assert specs["embed"]["embedding"] == P("tp", None)
    assert specs["head"]["kernel"] == P()
    assert specs["ln_head"]["scale"] == P()


def test_forward_equality_on_mesh(rng):
    assert len(jax.devices()) == 8
    mesh = get_mesh_nd({"dp": 2, "tp": 4})
    spec = small_spec()
    params, nt = spec.init_np(0)
    toks, mask, _ = batch(rng)

    ref, _ = jax.jit(lambda p, n: spec.apply(p, n, (toks, mask), False))(
        params, nt
    )
    sharded = shard_pytree(params, mesh, megatron_specs(params))
    out, _ = jax.jit(lambda p, n: spec.apply(p, n, (toks, mask), False))(
        sharded, nt
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_train_steps_match_single_device(rng):
    mesh = get_mesh_nd({"dp": 2, "tp": 4})
    spec = small_spec()
    ls = loss_step(spec)
    # sgd+momentum: updates are linear in the gradients, so float-level
    # reduction-order noise stays float-level in the params (adam's
    # 1/sqrt(v) normalization would amplify noise on near-zero grads)
    tx = optax.sgd(0.05, momentum=0.9)

    # single-device oracle: two plain steps on the global batch
    params, nt = spec.init_np(0)
    opt = tx.init(params)
    oracle = jax.jit(
        lambda p, n, o, b: _plain_step(ls, tx, p, n, o, b)
    )
    batches = [batch(rng), batch(rng)]
    ref_losses = []
    for b in batches:
        params, nt, opt, loss = oracle(params, nt, opt, b)
        ref_losses.append(float(loss))

    # SPMD dp=2 × tp=4
    engine = SPMDEngine(spec, ls, tx, mesh)
    p2, nt2, opt2 = engine.init_state(*spec.init_np(0))
    got_losses = []
    for b in batches:
        p2, nt2, opt2, loss = engine.run_step(p2, nt2, opt2, b)
        got_losses.append(float(loss))

    np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-5, atol=1e-6)
    ref_leaves = jax.tree.leaves(params)
    got_leaves = jax.tree.leaves(jax.device_get(p2))
    for r, g in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(g, r, rtol=3e-4, atol=3e-5)
    # the layout survived both donated steps
    assert_param_shardings(p2, engine.param_specs, mesh)


def test_params_actually_distributed(rng):
    """The big kernels must really be split over tp, not replicated."""
    mesh = get_mesh_nd({"dp": 2, "tp": 4})
    spec = small_spec()
    params, nt = spec.init_np(0)
    engine = SPMDEngine(spec, loss_step(spec), optax.sgd(0.01), mesh)
    p, nt, opt = engine.init_state(params, nt)
    kern = p["blocks_0"]["qkv"]["kernel"]
    # each device holds a [DIM, 3*DIM/4] slice
    shard_shapes = {s.data.shape for s in kern.addressable_shards}
    assert shard_shapes == {(DIM, 3 * DIM // 4)}
    emb = p["embed"]["embedding"]
    assert {s.data.shape for s in emb.addressable_shards} == {(VOCAB // 4, DIM)}


def test_mesh_trainer_end_to_end(rng):
    """MeshTrainer trains the transformer over dp×tp and learns."""
    from distkeras_tpu.data import Dataset
    from distkeras_tpu.trainers import MeshTrainer

    n = 64
    # every token encodes the class in its high bits, so the mean-pooled
    # encoder can learn the mapping fast
    y = rng.integers(0, CLASSES, size=(n,)).astype(np.int32)
    toks = (
        y[:, None] * (VOCAB // CLASSES)
        + rng.integers(0, VOCAB // CLASSES, size=(n, MAXLEN))
    ).astype(np.int32)
    mask = np.ones((n, MAXLEN), np.float32)
    ds = Dataset({"features": toks, "mask": mask, "label": y})

    trainer = MeshTrainer(
        small_spec(), loss="sparse_softmax_cross_entropy",
        worker_optimizer="adam", learning_rate=2e-3,
        mesh_shape={"dp": 2, "tp": 4}, batch_size=16, num_epoch=12,
        features_col=["features", "mask"], label_col="label",
    )
    params = trainer.train(ds, shuffle=True)
    losses = [r["loss"] for r in trainer.history.records if "loss" in r]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < 0.5 * np.mean(losses[:4])
    assert trainer.get_training_time() > 0
    # returned params are host pytrees usable for inference
    out, _ = small_spec().apply(
        params, trainer.trained_nt_, (toks[:8], mask[:8]), False
    )
    assert out.shape == (8, CLASSES)


def test_mesh_trainer_accepts_keras_model(rng):
    """The reference contract (hand a Keras model to a trainer) holds for
    the beyond-reference trainer too; Keras param lists have no layer names,
    so the Megatron rules replicate everything — a dp-only mesh run."""
    import keras

    from distkeras_tpu.data import Dataset
    from distkeras_tpu.trainers import MeshTrainer

    model = keras.Sequential([
        keras.layers.Input((16,)),
        keras.layers.Dense(32, activation="relu"),
        keras.layers.Dense(4),
    ])
    n = 64
    x = rng.normal(size=(n, 16)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    ds = Dataset({"features": x, "label": y})
    trainer = MeshTrainer(
        model, loss="sparse_softmax_cross_entropy", worker_optimizer="adam",
        learning_rate=5e-3, mesh_shape={"dp": 8}, batch_size=16, num_epoch=10,
    )
    out = trainer.train(ds, shuffle=True)
    assert out is model  # trained weights written back into the user's model
    preds = np.argmax(model.predict(x, verbose=0), axis=-1)
    assert np.mean(preds == y) > 0.8


def _plain_step(ls, tx, params, nt, opt, b):
    (loss, new_nt), grads = jax.value_and_grad(ls, has_aux=True)(
        params, nt, b
    )
    updates, opt = tx.update(grads, opt, params)
    return optax.apply_updates(params, updates), new_nt, opt, loss
