"""SRU classifier (models/sru.py).

Oracle: the associative-scan evaluation of the linear cell recurrence must
equal the sequential lax.scan evaluation exactly (same math, different
order), through values AND gradients; the classifier must behave like the
LSTM on the IMDB column layout (mask semantics, trainability).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models.sru import sru_classifier, sru_recurrence


def test_assoc_scan_matches_sequential_oracle(rng):
    gates = rng.normal(size=(3, 17, 3 * 8)).astype(np.float32)
    c_a, r_a = sru_recurrence(jnp.asarray(gates), impl="assoc")
    c_s, r_s = sru_recurrence(jnp.asarray(gates), impl="scan")
    np.testing.assert_allclose(np.asarray(c_a), np.asarray(c_s),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(r_a), np.asarray(r_s))


def test_assoc_gradients_match_sequential(rng):
    gates = rng.normal(size=(2, 11, 3 * 4)).astype(np.float32)

    def loss(g, impl):
        c, r = sru_recurrence(g, impl=impl)
        return jnp.sum(c * r)

    ga = jax.grad(lambda g: loss(g, "assoc"))(jnp.asarray(gates))
    gs = jax.grad(lambda g: loss(g, "scan"))(jnp.asarray(gates))
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gs),
                               rtol=1e-4, atol=1e-6)


def test_unknown_impl_rejected(rng):
    with pytest.raises(ValueError, match="impl"):
        sru_recurrence(jnp.zeros((1, 4, 6)), impl="nope")


@pytest.mark.slow  # impl-agreement integration; assoc-scan oracle stays fast
def test_classifier_impls_agree_and_mask_ignores_padding(rng):
    spec_a = sru_classifier(vocab=50, maxlen=12, embed_dim=16, hidden_dim=8,
                            depth=2, dtype=jnp.float32, impl="assoc")
    spec_s = sru_classifier(vocab=50, maxlen=12, embed_dim=16, hidden_dim=8,
                            depth=2, dtype=jnp.float32, impl="scan")
    params, nt = spec_a.init_np(0)
    toks = rng.integers(0, 50, size=(4, 12)).astype(np.int32)
    mask = np.ones((4, 12), np.float32)
    mask[:, 8:] = 0.0
    out_a, _ = spec_a.apply(params, nt, (toks, mask), False)
    out_s, _ = spec_s.apply(params, nt, (toks, mask), False)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_s),
                               rtol=1e-5, atol=1e-6)
    # the recurrence is causal and pooling is masked, so pad token VALUES
    # cannot influence the logits
    toks2 = toks.copy()
    toks2[:, 8:] = 7
    out_b, _ = spec_a.apply(params, nt, (toks2, mask), False)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=1e-6)


@pytest.mark.slow  # end-to-end training; assoc grads oracle stays fast
def test_sru_trains_on_imdb_config(rng):
    """Same trainer/columns as the IMDB BASELINE config (DynSGD, padded
    tokens + mask) — the SRU must learn the synthetic sentiment task."""
    from distkeras_tpu.datasets import imdb
    from distkeras_tpu.trainers import DynSGD

    train, _ = imdb(n_train=512, n_test=64, vocab=500, maxlen=32)
    spec = sru_classifier(vocab=500, maxlen=32, embed_dim=16, hidden_dim=16,
                          dtype=jnp.float32)
    t = DynSGD(spec, loss="sparse_softmax_cross_entropy",
               worker_optimizer="adam", learning_rate=2e-3, num_workers=8,
               batch_size=8, communication_window=2, num_epoch=3,
               features_col=["features", "mask"], label_col="label")
    t.train(train, shuffle=True)
    losses = [float(l) for l in t.get_history().losses()]
    assert np.isfinite(losses).all()
    # same bar as the LSTM's learns-on-mesh test (test_models.py)
    assert np.mean(losses[-3:]) < losses[0]
