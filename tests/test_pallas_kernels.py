"""Pallas fused-Adam kernel vs the optax oracle (interpret mode on CPU),
plus its integration through the trainer stack (vmap + scan over the kernel).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.pallas_kernels import FusedAdamState, fused_adam
from tests.test_trainers import blobs_dataset, final_loss, model_spec


def random_tree(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "conv": rng.normal(size=(3, 3, 4, 8)).astype(np.float32),
        "bias": rng.normal(size=(8,)).astype(np.float32),   # tiny, pad-heavy
        "dense": rng.normal(size=(200, 33)).astype(np.float32),  # odd cols
    }


def test_fused_adam_matches_optax_over_steps():
    lr = 1e-2
    params = random_tree(0)
    fused = fused_adam(lr, interpret=True)
    oracle = optax_adam = __import__("optax").adam(lr)

    sf = fused.init(params)
    so = oracle.init(params)
    p_f = jax.tree.map(jnp.asarray, params)
    p_o = jax.tree.map(jnp.asarray, params)
    for step in range(4):
        grads = random_tree(step + 10)
        uf, sf = fused.update(grads, sf)
        uo, so = optax_adam.update(grads, so)
        for a, b in zip(jax.tree.leaves(uf), jax.tree.leaves(uo)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)
        p_f = __import__("optax").apply_updates(p_f, uf)
        p_o = __import__("optax").apply_updates(p_o, uo)
    for a, b in zip(jax.tree.leaves(p_f), jax.tree.leaves(p_o)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    # moments updated, not zero
    assert float(jnp.abs(jax.tree.leaves(sf.mu)[0]).sum()) > 0


def test_fused_adam_state_is_checkpointable_pytree():
    fused = fused_adam(1e-3, interpret=True)
    state = fused.init({"w": jnp.ones((4, 4))})
    from distkeras_tpu.utils import deserialize_weights, serialize_weights

    back = deserialize_weights(serialize_weights(state))
    assert isinstance(back, FusedAdamState)
    assert int(back.count) == 0


def test_fused_adam_under_vmap_matches_per_row():
    """The engine vmaps optimizer.update over the worker axis — the kernel
    must batch correctly."""
    lr = 1e-2
    fused = fused_adam(lr, interpret=True)
    W = 4
    params = {"w": jnp.arange(W * 24, dtype=jnp.float32).reshape(W, 24) / 10}
    grads = {"w": jnp.ones((W, 24), jnp.float32) * 0.3}
    state = jax.vmap(fused.init)(params)
    u_batched, _ = jax.vmap(fused.update)(grads, state)
    for i in range(W):
        pi = {"w": params["w"][i]}
        gi = {"w": grads["w"][i]}
        ui, _ = fused.update(gi, fused.init(pi))
        np.testing.assert_allclose(np.asarray(u_batched["w"][i]),
                                   np.asarray(ui["w"]), rtol=1e-5, atol=1e-7)


def test_trainer_with_fused_adam_learns_on_mesh():
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=2048)
    t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
             worker_optimizer="fused_adam", learning_rate=5e-3,
             num_workers=8, batch_size=32, communication_window=2,
             num_epoch=3)
    t.train(ds, shuffle=True)
    assert final_loss(t) < 0.5, final_loss(t)


def test_fused_adam_vs_adam_trainer_equivalence():
    """Same data, same seed: fused_adam must track optax adam closely."""
    from distkeras_tpu import ADAG

    ds = blobs_dataset(n=512)
    common = dict(loss="sparse_softmax_cross_entropy", learning_rate=1e-2,
                  num_workers=4, batch_size=16, communication_window=2,
                  num_epoch=1, seed=2)
    p1 = ADAG(model_spec(), worker_optimizer="adam", **common).train(ds)
    p2 = ADAG(model_spec(), worker_optimizer="fused_adam", **common).train(ds)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
