"""Checkpoint error paths and the elastic-resume warning (ISSUE 4
satellites): the recovery layer leans on these — a restart that restores
from a truncated snapshot must fail loudly and namefully, never resume
from garbage."""

import pickle

import numpy as np
import pytest

from distkeras_tpu import checkpoint as ckpt


def test_warn_elastic_resume_message_and_category():
    """The shared elastic-resume warning names both worker counts (it is
    the only signal the user gets that optimizer state restarted)."""
    with pytest.warns(UserWarning, match=r"elastic resume.*2 workers.*4"):
        ckpt.warn_elastic_resume(2, 4)
    # shrinking is elastic too, same path
    with pytest.warns(UserWarning, match=r"checkpoint has 8 workers"):
        ckpt.warn_elastic_resume(8, 1)


def test_sharded_restore_missing_shard_file_names_it(tmp_path):
    """A deleted/unsynced shard file fails with FileNotFoundError naming
    the missing file and the writing process count."""
    tree = {"w": np.arange(8, dtype=np.float32)}
    ckpt._save_sharded(tmp_path, tree, step=2)
    shard = ckpt._shard_file(tmp_path, 2, 0, 1)
    shard.unlink()
    with pytest.raises(FileNotFoundError, match=shard.name):
        ckpt.restore_checkpoint(tmp_path, step=2)


def test_sharded_restore_truncated_shard_file(tmp_path):
    """A torn write (crash mid-copy) surfaces as ValueError naming the
    shard file — not a bare unpickling error from the wrong layer."""
    tree = {"w": np.arange(16, dtype=np.float32)}
    ckpt._save_sharded(tmp_path, tree, step=1)
    shard = ckpt._shard_file(tmp_path, 1, 0, 1)
    blob = shard.read_bytes()
    shard.write_bytes(blob[: len(blob) // 2])  # torn mid-write
    with pytest.raises(ValueError, match=rf"{shard.name}.*truncated|truncated.*{shard.name}"):
        ckpt.restore_checkpoint(tmp_path, step=1)


def test_sharded_restore_truncated_meta_file(tmp_path):
    """Same contract for the meta file (the other half of the format)."""
    ckpt._save_sharded(tmp_path, {"w": np.ones(4, np.float32)}, step=5)
    meta = ckpt._meta_file(tmp_path, 5)
    meta.write_bytes(meta.read_bytes()[:10])
    with pytest.raises(ValueError, match="truncated or corrupt"):
        ckpt.restore_checkpoint(tmp_path, step=5)


def test_sharded_restore_corrupt_not_just_short(tmp_path):
    """Garbage of the right length (bit rot, not truncation) is caught by
    the same typed error."""
    tree = {"w": np.arange(8, dtype=np.float32)}
    ckpt._save_sharded(tmp_path, tree, step=0)
    shard = ckpt._shard_file(tmp_path, 0, 0, 1)
    shard.write_bytes(b"\x00" * len(shard.read_bytes()))
    with pytest.raises(ValueError, match="truncated or corrupt"):
        ckpt.restore_checkpoint(tmp_path, step=0)


def test_sharded_restore_survives_intact_roundtrip(tmp_path):
    """Control: the untampered file restores exactly (guards against the
    new error wrapping catching healthy loads)."""
    tree = {"w": np.arange(8, dtype=np.float32), "b": np.ones(3, np.int32)}
    ckpt._save_sharded(tmp_path, tree, step=7)
    got, step = ckpt.restore_checkpoint(tmp_path)
    assert step == 7
    np.testing.assert_array_equal(got["w"], tree["w"])
    np.testing.assert_array_equal(got["b"], tree["b"])


def test_shard_payload_format_is_pickle_of_shards_dict(tmp_path):
    """Pin the on-disk shard schema the error paths assume ({'shards':
    {(leaf, starts): array}}): a format change must update the torn-write
    detection with it."""
    ckpt._save_sharded(tmp_path, {"w": np.arange(4, dtype=np.float32)},
                       step=0)
    payload = pickle.loads(
        ckpt._shard_file(tmp_path, 0, 0, 1).read_bytes()
    )
    assert set(payload) == {"shards"}
    (key, data), = payload["shards"].items()
    assert key == (0, (0,))
    np.testing.assert_array_equal(data, np.arange(4, dtype=np.float32))
