import numpy as np
import pytest

from distkeras_tpu.data import Dataset
from distkeras_tpu import transformers as T


def make_ds(n=100):
    feats = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    labels = (np.arange(n) % 3).astype(np.int32)
    return Dataset.from_arrays(feats, labels)


def test_basic_frame_ops():
    ds = make_ds(10)
    assert len(ds) == 10
    assert set(ds.columns) == {"features", "label"}
    ds2 = ds.with_column("extra", np.ones(10))
    assert "extra" in ds2 and "extra" not in ds
    assert len(ds2.select(["extra"]).columns) == 1
    tr, te = ds.split(0.7, seed=0)
    assert len(tr) == 7 and len(te) == 3


def test_column_length_mismatch_raises():
    with pytest.raises(ValueError):
        Dataset({"a": np.ones(3), "b": np.ones(4)})


def test_shuffle_is_permutation():
    ds = make_ds(50)
    sh = ds.shuffle(seed=3)
    assert not np.array_equal(sh["label"], ds["label"])
    assert sorted(sh["features"][:, 0].tolist()) == sorted(
        ds["features"][:, 0].tolist()
    )


def test_superbatch_layout_rows_disjoint_and_ordered():
    """Worker w / window t / batch b must map to distinct dataset rows in the
    [W, window, B, ...] layout, with each worker's stream disjoint."""
    n, W, B, win = 96, 4, 3, 2
    ds = make_ds(n)
    sbs = list(ds.superbatches(W, B, win, ["features", "label"]))
    assert len(sbs) == n // (W * B * win)
    feats, labels = sbs[0]
    assert feats.shape == (W, win, B, 4)
    assert labels.shape == (W, win, B)
    # Collect all row ids (features col 0 is 4*row) across the superbatch
    row_ids = (feats[..., 0].reshape(-1) / 4).astype(int)
    assert len(set(row_ids.tolist())) == W * B * win  # all distinct
    # Window-major interleave: worker w, window t draws from block t
    flat = feats[..., 0] / 4  # [W, win, B]
    for t in range(win):
        block = flat[:, t, :].reshape(-1)
        expected = np.arange(t * W * B, (t + 1) * W * B)
        assert set(block.astype(int).tolist()) == set(expected.tolist())


def test_superbatch_too_small_raises():
    ds = make_ds(10)
    with pytest.raises(ValueError):
        list(ds.superbatches(8, 4, 2, ["features"]))


def test_batches_single_stream():
    ds = make_ds(64)
    bs = list(ds.batches(16, ["features", "label"]))
    assert len(bs) == 4
    x, y = bs[0]
    assert x.shape == (16, 4) and y.shape == (16,)


def test_onehot_transformer():
    ds = make_ds(9)
    out = T.OneHotTransformer(3, input_col="label", output_col="oh").transform(ds)
    oh = out["oh"]
    assert oh.shape == (9, 3)
    assert np.array_equal(np.argmax(oh, -1), ds["label"])
    assert np.allclose(oh.sum(-1), 1.0)


def test_minmax_transformer():
    ds = Dataset({"features": np.array([[0.0], [127.5], [255.0]], np.float32)})
    out = T.MinMaxTransformer(0.0, 1.0, 0.0, 255.0).transform(ds)
    assert np.allclose(out["features"].reshape(-1), [0.0, 0.5, 1.0])


def test_reshape_transformer():
    ds = Dataset({"features": np.zeros((5, 784), np.float32)})
    out = T.ReshapeTransformer("features", "img", (28, 28, 1)).transform(ds)
    assert out["img"].shape == (5, 28, 28, 1)


def test_label_index_transformer():
    ds = Dataset({"prediction": np.array([[0.1, 0.9], [0.8, 0.2]], np.float32)})
    out = T.LabelIndexTransformer().transform(ds)
    assert np.array_equal(out["prediction_index"], [1, 0])


def test_sequence_pad_transformer():
    seqs = np.asarray([np.array([5, 6, 7]), np.array([1])], dtype=object)
    ds = Dataset({"sequence": seqs})
    out = T.SequencePadTransformer(5, input_col="sequence").transform(ds)
    assert np.array_equal(out["tokens"][0], [5, 6, 7, 0, 0])
    assert np.array_equal(out["mask"][1], [1, 0, 0, 0, 0])


def test_pipeline_composes():
    ds = make_ds(9)
    pipe = T.TransformerPipeline([
        T.OneHotTransformer(3, input_col="label", output_col="oh"),
        T.MinMaxTransformer(0, 1, 0, 400, input_col="features"),
    ])
    out = pipe.transform(ds)
    assert "oh" in out and out["features"].max() <= 1.0


def test_dense_transformer_sparse_rows():
    rows = np.asarray(
        [(np.array([0, 2]), np.array([1.0, 3.0])),
         (np.array([1]), np.array([2.0]))],
        dtype=object,
    )
    ds = Dataset({"features": rows})
    out = T.DenseTransformer(dim=4).transform(ds)
    assert np.array_equal(out["features_dense"][0], [1.0, 0.0, 3.0, 0.0])
    assert np.array_equal(out["features_dense"][1], [0.0, 2.0, 0.0, 0.0])


def test_worker_shards_matches_superbatch_interleave():
    n, W, B, win = 96, 4, 3, 2
    ds = make_ds(n)
    shards = ds.worker_shards(W, B, win, ["features", "label"])
    feats = shards[0]
    assert feats.shape == (W, (n // (W * B * win)) * win * B, 4)
    # reconstruct the streaming view and compare row-for-row
    sbs = list(ds.superbatches(W, B, win, ["features", "label"]))
    for s, (sf, _) in enumerate(sbs):
        for w in range(W):
            got = feats[w, s * win * B : (s + 1) * win * B]
            expected = sf[w].reshape(win * B, 4)
            assert np.array_equal(got, expected)


def test_worker_shards_cover_all_wraps_tail():
    ds = make_ds(100)
    shards = ds.worker_shards(2, 8, 2, ["features"], seed=1, cover_all=True)
    rows = (shards[0][..., 0].reshape(-1) / 4).astype(int)
    assert set(rows.tolist()) == set(range(100))  # every row present


# -- streaming input pipeline (prefetch_to_device) --------------------------


def test_prefetch_preserves_order_and_applies_place():
    from distkeras_tpu.data import prefetch_to_device

    items = [np.full((4,), i, np.float32) for i in range(10)]
    out = list(prefetch_to_device(iter(items), lambda x: x + 1, depth=3))
    assert len(out) == 10
    for i, o in enumerate(out):
        np.testing.assert_array_equal(o, np.full((4,), i + 1, np.float32))


def test_prefetch_propagates_producer_errors():
    from distkeras_tpu.data import prefetch_to_device

    def gen():
        yield np.zeros(2)
        raise RuntimeError("boom mid-epoch")

    it = prefetch_to_device(gen(), lambda x: x, depth=2)
    next(it)
    with pytest.raises(RuntimeError, match="boom mid-epoch"):
        next(it)


def test_prefetch_early_close_unblocks_producer():
    import threading
    import time

    from distkeras_tpu.data import prefetch_to_device

    before = set(threading.enumerate())
    it = prefetch_to_device(iter(range(10_000)), lambda x: x, depth=1)
    assert next(it) == 0
    spawned = [t for t in threading.enumerate() if t not in before]
    assert len(spawned) == 1  # exactly the producer thread
    it.close()  # consumer bails early: producer must unblock and exit
    deadline = time.time() + 5
    while spawned[0].is_alive() and time.time() < deadline:
        time.sleep(0.05)
    assert not spawned[0].is_alive()


def test_prefetch_error_delivery_outlives_slow_consumers():
    """A producer error with a FULL queue must still reach a consumer that
    drains slowly (>1s per step) — the sentinel may never be dropped."""
    import time

    from distkeras_tpu.data import prefetch_to_device

    def gen():
        yield 1
        yield 2
        raise RuntimeError("late boom")

    it = prefetch_to_device(gen(), lambda x: x, depth=1)
    got = [next(it)]
    time.sleep(1.3)  # queue full + error pending while consumer is "busy"
    got.append(next(it))
    assert got == [1, 2]
    with pytest.raises(RuntimeError, match="late boom"):
        next(it)


def test_prefetch_rejects_bad_depth():
    from distkeras_tpu.data import prefetch_to_device

    with pytest.raises(ValueError, match="depth"):
        list(prefetch_to_device(iter([]), lambda x: x, depth=0))


def test_streaming_prefetch_is_bit_identical_adag():
    """The prefetched feed is the same batches in the same order through
    the same placement — training must be bit-identical to prefetch=0."""
    import jax

    from distkeras_tpu import ADAG
    from tests.test_trainers import blobs_dataset, model_spec

    def run(prefetch):
        t = ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                 worker_optimizer="sgd", learning_rate=0.1, num_workers=4,
                 batch_size=16, communication_window=2, num_epoch=2,
                 device_data=False, prefetch=prefetch, seed=3)
        return t.train(blobs_dataset(n=1024), shuffle=True)

    a, b = run(0), run(2)
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_streaming_prefetch_is_bit_identical_mesh():
    import jax
    import jax.numpy as jnp

    from distkeras_tpu.models import mlp
    from distkeras_tpu.trainers import MeshTrainer
    from tests.test_trainers import blobs_dataset

    def run(prefetch):
        t = MeshTrainer(
            mlp(input_shape=(16,), hidden=(32,), num_classes=4,
                dtype=jnp.float32),
            loss="sparse_softmax_cross_entropy", worker_optimizer="adam",
            learning_rate=1e-3, mesh_shape={"dp": 8}, batch_size=32,
            num_epoch=2, seed=5, input_mode="stream", prefetch=prefetch,
        )
        return t.train(blobs_dataset(n=512))

    a, b = run(0), run(2)
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
