"""Causal LM + KV-cached autoregressive decoding (models/lm.py).

The load-bearing oracle: decoding one token at a time against the KV cache
must produce exactly the same logits as re-running the full causal forward
on the growing sequence — cache decode is an optimization, never a
different model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models import generate, next_token_dataset, transformer_lm
from distkeras_tpu.models.lm import TransformerLM

VOCAB, MAXLEN, DIM, HEADS, DEPTH = 64, 32, 32, 4, 2


@pytest.fixture(scope="module")
def lm():
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=DEPTH, dtype=jnp.float32)
    params, _ = spec.init_np(0)
    return spec, params


def _assert_cached_decode_matches_full(module, params, toks, lp, *,
                                       check_prefill_logits=True,
                                       rtol=2e-4, atol=2e-4):
    """Prefill on ``toks[:, :lp]`` + jitted cached decode over the rest must
    match ONE full forward over the whole sequence, position by position:
    causal attention makes ``full[:, pos]`` the prediction after consuming
    exactly ``toks[:, :pos+1]`` (causality of the full forward itself is
    pinned in test_decode_step_matches_full_forward). Returns the final
    caches."""
    full = np.asarray(module.apply({"params": params}, toks))
    logits_pre, caches = module.apply(
        {"params": params}, toks[:, :lp], method=TransformerLM.prefill
    )
    if check_prefill_logits:
        np.testing.assert_allclose(
            np.asarray(logits_pre), full[:, :lp], rtol=rtol, atol=atol
        )
    step = jax.jit(
        lambda tok, caches, pos: module.apply(
            {"params": params}, tok, caches, pos,
            method=TransformerLM.decode_step,
        )
    )
    for pos in range(lp, toks.shape[1]):
        step_logits, caches = step(toks[:, pos], caches, pos)
        np.testing.assert_allclose(
            np.asarray(step_logits), full[:, pos],
            rtol=rtol, atol=atol, err_msg=f"pos={pos}",
        )
    return caches


def test_decode_step_matches_full_forward(lm):
    """Prefill + N cached decode steps == full forward logits, position by
    position (f32, exact math path).

    The oracle is ONE full forward over the whole sequence: causal
    attention makes ``full[:, pos]`` the model's prediction after
    consuming exactly ``toks[:, :pos+1]`` — verified directly below by a
    prefix re-run — so every decode position checks against it without
    re-running a growing-prefix forward per step."""
    spec, params = lm
    module = spec.module
    rng = np.random.default_rng(0)
    toks = rng.integers(0, VOCAB, size=(3, 12)).astype(np.int32)

    full = np.asarray(module.apply({"params": params}, toks))
    # causality of the oracle itself: a prefix re-run reproduces its rows
    lp = 5
    prefix = module.apply({"params": params}, toks[:, :lp])
    np.testing.assert_allclose(np.asarray(prefix), full[:, :lp],
                               rtol=2e-4, atol=2e-4)

    logits_pre, caches = module.apply(
        {"params": params}, toks[:, :lp], method=TransformerLM.prefill
    )
    np.testing.assert_allclose(
        np.asarray(logits_pre), full[:, :lp], rtol=2e-4, atol=2e-4
    )
    step = jax.jit(
        lambda tok, caches, pos: module.apply(
            {"params": params}, tok, caches, pos,
            method=TransformerLM.decode_step,
        )
    )
    for pos in range(lp, toks.shape[1]):
        step_logits, caches = step(toks[:, pos], caches, pos)
        np.testing.assert_allclose(
            np.asarray(step_logits), full[:, pos],
            rtol=2e-4, atol=2e-4,
        )


def test_greedy_generation_matches_uncached_argmax(lm):
    """generate(temperature=0) equals the uncached greedy stream — the
    cache changes cost, not output. Greedy self-consistency needs one
    full forward on the emitted sequence: token t+1 must be the argmax of
    the full model's logits at position t given the emitted prefix (the
    causal forward's row t sees exactly that prefix)."""
    spec, params = lm
    module = spec.module
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, VOCAB, size=(2, 6)).astype(np.int32)
    out = generate(spec, params, prompt, max_new_tokens=8)
    assert out.shape == (2, 14)
    assert np.array_equal(out[:, :6], prompt)

    full = np.asarray(module.apply({"params": params}, jnp.asarray(out)))
    want = np.argmax(full[:, 5:-1], axis=-1)
    np.testing.assert_array_equal(out[:, 6:], want)


def test_sampled_generation_reproducible_and_valid(lm):
    spec, params = lm
    prompt = np.zeros((4, 4), np.int32)
    a = generate(spec, params, prompt, max_new_tokens=6, temperature=1.0,
                 top_k=8, seed=7)
    b = generate(spec, params, prompt, max_new_tokens=6, temperature=1.0,
                 top_k=8, seed=7)
    c = generate(spec, params, prompt, max_new_tokens=6, temperature=1.0,
                 top_k=8, seed=8)
    np.testing.assert_array_equal(a, b)  # same seed → same tokens
    assert not np.array_equal(a, c)      # different seed → different draw
    assert a.min() >= 0 and a.max() < VOCAB


def test_top_k_restricts_support(lm):
    """With top_k=1, sampling at any temperature degenerates to greedy."""
    spec, params = lm
    prompt = np.ones((2, 5), np.int32)
    greedy = generate(spec, params, prompt, max_new_tokens=5)
    k1 = generate(spec, params, prompt, max_new_tokens=5, temperature=2.0,
                  top_k=1, seed=3)
    np.testing.assert_array_equal(greedy, k1)


def test_top_p_restricts_support(lm):
    """Sampled tokens stay inside the numpy-computed nucleus; a tiny top_p
    degenerates to greedy; top_p=1.0 is a no-op filter."""
    spec, params = lm
    module = spec.module
    prompt = np.ones((2, 5), np.int32)

    greedy = generate(spec, params, prompt, max_new_tokens=5)
    p_tiny = generate(spec, params, prompt, max_new_tokens=5,
                      temperature=2.0, top_p=1e-6, seed=3)
    np.testing.assert_array_equal(greedy, p_tiny)

    plain = generate(spec, params, prompt, max_new_tokens=6,
                     temperature=1.0, seed=11)
    p_one = generate(spec, params, prompt, max_new_tokens=6,
                     temperature=1.0, top_p=1.0, seed=11)
    np.testing.assert_array_equal(plain, p_one)

    # every sampled first token lies in the nucleus of its own distribution
    top_p = 0.6
    logits = np.asarray(
        module.apply({"params": params}, jnp.asarray(prompt))
    )[:, -1]
    out = generate(spec, params, prompt, max_new_tokens=1, temperature=1.0,
                   top_p=top_p, seed=5)
    for row, tok in enumerate(out[:, -1]):
        order = np.argsort(-logits[row])
        probs = np.exp(logits[row][order] - logits[row][order].max())
        probs /= probs.sum()
        before = np.cumsum(probs) - probs
        nucleus = set(order[before < top_p])
        assert int(tok) in nucleus


def test_generate_rejects_bad_top_p(lm):
    spec, params = lm
    for bad in (0.0, -0.5, 1.5):
        with pytest.raises(ValueError, match="top_p"):
            generate(spec, params, np.zeros((1, 4), np.int32),
                     max_new_tokens=2, temperature=1.0, top_p=bad)


def test_generate_validates_inputs(lm):
    spec, params = lm
    with pytest.raises(ValueError, match="maxlen"):
        generate(spec, params, np.zeros((1, 30), np.int32), max_new_tokens=5)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(spec, params, np.zeros((1, 4), np.int32), max_new_tokens=0)
    with pytest.raises(ValueError, match="batch, length"):
        generate(spec, params, np.zeros((4,), np.int32), max_new_tokens=2)
    with pytest.raises(TypeError, match="TransformerLM"):
        from distkeras_tpu.models import mlp

        generate(mlp(), params, np.zeros((1, 4), np.int32), max_new_tokens=2)


def test_lm_trains_next_token_with_trainer():
    """The LM is a first-class trainer citizen: ADAG on the 8-device mesh
    drives next-token loss down on a deterministic-cycle language, and the
    trained model then generates the cycle greedily."""
    from distkeras_tpu import ADAG

    period = 8
    rows, length = 512, 16
    rng = np.random.default_rng(0)
    starts = rng.integers(0, period, size=(rows, 1))
    grid = (starts + np.arange(length + 1)[None]) % period  # token = pos%8
    ds = next_token_dataset(grid)
    assert ds["features"].shape == (rows, length)
    assert np.array_equal(ds["features"][:, 1:], ds["label"][:, :-1])

    spec = transformer_lm(vocab=period, maxlen=32, dim=32, heads=4, depth=2,
                          dtype=jnp.float32)
    t = ADAG(spec, loss="sparse_softmax_cross_entropy",
             worker_optimizer="adam", learning_rate=5e-3, num_workers=4,
             batch_size=32, communication_window=2, num_epoch=6)
    t.train(ds, shuffle=True)
    losses = [float(l) for l in t.get_history().losses()]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < 0.5 * np.mean(losses[:4])

    prompt = np.tile(np.arange(6) % period, (2, 1)).astype(np.int32)
    out = generate(spec, t.trained_params_, prompt, max_new_tokens=8)
    expect = (np.arange(6, 14) % period)[None].repeat(2, axis=0)
    assert np.array_equal(out[:, 6:], expect)


def test_generator_predictor_appends_column(lm):
    """GeneratorPredictor chunks prompts to a static batch and appends the
    generated-token column; equal to calling generate() directly."""
    from distkeras_tpu.data import Dataset
    from distkeras_tpu.predictors import GeneratorPredictor

    spec, params = lm
    rng = np.random.default_rng(2)
    prompts = rng.integers(0, VOCAB, size=(11, 6)).astype(np.int32)  # 11 % 4 != 0
    ds = Dataset({"features": prompts})
    p = GeneratorPredictor(spec, params, max_new_tokens=5, batch_size=4)
    out = p.predict(ds)
    assert out["generated"].shape == (11, 5)
    direct = generate(spec, params, prompts, max_new_tokens=5)
    np.testing.assert_array_equal(out["generated"], direct[:, 6:])

    with pytest.raises(TypeError, match="TransformerLM"):
        from distkeras_tpu.models import mlp

        GeneratorPredictor(mlp(), params)


def test_generate_eos_id_stops_rows_and_pads(lm):
    """eos_id: each row matches the eos-free greedy stream up to and
    including its first eos, then pads with eos_id — static output shape,
    mask-and-carry done flags (the serving tier's retire rule)."""
    spec, params = lm
    rng = np.random.default_rng(9)
    prompts = rng.integers(0, VOCAB, size=(3, 6)).astype(np.int32)
    free = generate(spec, params, prompts, max_new_tokens=10)
    # pick the token row 0 emits at step 3 as eos: row 0 must stop there
    eos = int(free[0, 6 + 3])
    out = generate(spec, params, prompts, max_new_tokens=10, eos_id=eos)
    assert out.shape == free.shape
    cuts = []
    for b in range(3):
        new = free[b, 6:]
        hits = np.where(new == eos)[0]
        cut = hits[0] + 1 if hits.size else 10
        cuts.append(cut)
        np.testing.assert_array_equal(out[b, :6 + cut], free[b, :6 + cut])
        assert (out[b, 6 + cut:] == eos).all()
    assert min(cuts) < 10, "eos token never fired — test is vacuous"

    from distkeras_tpu.serving import per_row_new_token_counts

    np.testing.assert_array_equal(
        per_row_new_token_counts(out[:, 6:], eos), cuts
    )

    with pytest.raises(ValueError, match="eos_id"):
        generate(spec, params, prompts, 4, eos_id=VOCAB)


def test_generate_eos_id_sampled_path(lm):
    """eos works with temperature/top_k sampling and stays deterministic
    per seed (its own fold_in key schedule)."""
    spec, params = lm
    prompt = np.ones((2, 5), np.int32)
    a = generate(spec, params, prompt, 12, temperature=0.9, top_k=12,
                 seed=4, eos_id=3)
    b = generate(spec, params, prompt, 12, temperature=0.9, top_k=12,
                 seed=4, eos_id=3)
    np.testing.assert_array_equal(a, b)
    for row in a[:, 5:]:
        hits = np.where(row == 3)[0]
        if hits.size:
            assert (row[hits[0]:] == 3).all()


def test_generator_predictor_eos_and_per_row_counts(lm):
    """Satellite: eos_id now rides the sampling path (beams=1) instead of
    raising, and per_row_new_tokens adds the serving-tier count column."""
    from distkeras_tpu.data import Dataset
    from distkeras_tpu.predictors import GeneratorPredictor
    from distkeras_tpu.serving import per_row_new_token_counts

    spec, params = lm
    rng = np.random.default_rng(5)
    prompts = rng.integers(0, VOCAB, size=(6, 6)).astype(np.int32)
    free = generate(spec, params, prompts, max_new_tokens=8)
    eos = int(free[0, 6])  # row 0's first new token → count 1 for row 0
    p = GeneratorPredictor(spec, params, max_new_tokens=8, batch_size=4,
                           eos_id=eos, per_row_new_tokens=True)
    out = p.predict(Dataset({"features": prompts}))
    assert out["generated"].shape == (6, 8)
    np.testing.assert_array_equal(
        out["generated_new_tokens"],
        per_row_new_token_counts(out["generated"], eos),
    )
    assert out["generated_new_tokens"][0] == 1
    # length_penalty stays beam-only
    with pytest.raises(ValueError, match="length_penalty"):
        GeneratorPredictor(spec, params, length_penalty=0.5)


def test_generate_single_token_and_program_reuse(lm):
    """max_new_tokens=1 (zero-length scan) works, and repeated generate()
    calls with one decode config reuse one compiled program."""
    from distkeras_tpu.models.lm import _generate_program

    spec, params = lm
    prompt = np.zeros((2, 4), np.int32)
    out = generate(spec, params, prompt, max_new_tokens=1)
    assert out.shape == (2, 5)
    full = spec.module.apply({"params": params}, jnp.asarray(prompt))
    np.testing.assert_array_equal(
        out[:, -1], np.asarray(jnp.argmax(full[:, -1], -1)))
    assert _generate_program(spec.module, 1, 0.0, None) is \
        _generate_program(spec.module, 1, 0.0, None)


@pytest.mark.slow  # bf16 dtype-path variant; the f32 cache-parity oracle stays fast
def test_decode_matches_full_forward_bf16():
    """The decode step follows attention_reference's exact dtype path, so
    cache-vs-full parity holds in the default bf16 too (logit differences at
    the bf16 resolution floor, not a different math path)."""
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=DEPTH, dtype=jnp.bfloat16)
    params, _ = spec.init_np(0)
    module = spec.module
    rng = np.random.default_rng(3)
    toks = rng.integers(0, VOCAB, size=(2, 10)).astype(np.int32)
    _, caches = module.apply(
        {"params": params}, toks[:, :9], method=TransformerLM.prefill
    )
    step_logits, _ = module.apply(
        {"params": params}, toks[:, 9], caches, 9,
        method=TransformerLM.decode_step,
    )
    full = module.apply({"params": params}, toks)
    np.testing.assert_allclose(
        np.asarray(step_logits), np.asarray(full[:, -1]), rtol=0, atol=1e-3
    )


def test_generate_rejects_bad_top_k(lm):
    spec, params = lm
    prompt = np.zeros((1, 4), np.int32)
    for bad in (0, -3, VOCAB + 1):
        with pytest.raises(ValueError, match="top_k"):
            generate(spec, params, prompt, max_new_tokens=2,
                     temperature=1.0, top_k=bad)


def test_windowed_lm_decode_matches_full_forward():
    """Sliding-window LM: prefill + cached decode (cache masked to the band)
    equals the full windowed forward at every position."""
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=DEPTH, dtype=jnp.float32, attn_window=6)
    params, _ = spec.init_np(0)
    rng = np.random.default_rng(1)
    toks = rng.integers(0, VOCAB, size=(2, 14)).astype(np.int32)
    _assert_cached_decode_matches_full(spec.module, params, toks, lp=4)


def test_windowed_lm_generates(lm):
    """generate() runs end-to-end on a windowed LM and differs from the
    unwindowed model's continuation (the window actually binds)."""
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, VOCAB, size=(2, 10)).astype(np.int32)
    specw = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                           depth=DEPTH, dtype=jnp.float32, attn_window=3)
    params, _ = specw.init_np(0)
    outw = generate(specw, params, prompt, max_new_tokens=8)
    assert outw.shape == (2, 18)
    assert (outw[:, :10] == prompt).all()
    spec_full = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM,
                               heads=HEADS, depth=DEPTH, dtype=jnp.float32)
    out_full = generate(spec_full, params, prompt, max_new_tokens=8)
    assert (outw != out_full).any()


def test_flash_lm_accepts_ragged_prompt():
    """attn_impl='flash' on the LM family dispatches as 'auto': a prompt
    whose length is not a tile multiple must prefill (falling back to the
    XLA path) instead of erroring."""
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=DEPTH, dtype=jnp.float32, attn_impl="flash")
    params, _ = spec.init_np(0)
    prompt = np.arange(10, dtype=np.int32)[None].repeat(2, axis=0)
    out = generate(spec, params, prompt, max_new_tokens=4)
    assert out.shape == (2, 14)


def test_gqa_kv_heads_equal_heads_is_mha():
    """kv_heads == heads is EXACTLY the MHA model: same parameter tree,
    same logits (the fused qkv split reduces to thirds)."""
    spec_mha = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM,
                              heads=HEADS, depth=DEPTH, dtype=jnp.float32)
    spec_gqa = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM,
                              heads=HEADS, depth=DEPTH, dtype=jnp.float32,
                              kv_heads=HEADS)
    params, _ = spec_mha.init_np(0)
    pg, _ = spec_gqa.init_np(0)
    assert jax.tree.structure(params) == jax.tree.structure(pg)
    toks = np.arange(8, dtype=np.int32)[None].repeat(2, axis=0)
    a = spec_mha.module.apply({"params": params}, toks)
    b = spec_gqa.module.apply({"params": params}, toks)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gqa_decode_matches_full_forward():
    """GQA (2 kv heads under 4 query heads): prefill + cached decode against
    the Hkv-wide cache equals the full grouped forward at every position."""
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=DEPTH, dtype=jnp.float32, kv_heads=2)
    params, _ = spec.init_np(0)
    module = spec.module
    rng = np.random.default_rng(3)
    toks = rng.integers(0, VOCAB, size=(2, 12)).astype(np.int32)

    _, caches = module.apply(
        {"params": params}, toks[:, :4], method=TransformerLM.prefill
    )
    kc, vc = caches[0]
    assert kc.shape == (2, MAXLEN, 2, DIM // HEADS)  # Hkv-wide cache
    _assert_cached_decode_matches_full(module, params, toks, lp=4)


@pytest.mark.slow  # mqa train+generate integration; gqa decode parity pin stays fast
def test_mqa_trains_and_generates():
    """MQA (kv_heads=1) end to end: the LM learns a deterministic next-token
    rule through the trainer API and continues it at decode time."""
    import jax.numpy as jnp2

    from distkeras_tpu.trainers import ADAG

    rng = np.random.default_rng(0)
    V, Lp1 = 32, 17
    start = rng.integers(0, V, size=(512, 1))
    rows = (start + np.arange(Lp1)) % V
    spec = transformer_lm(vocab=V, maxlen=64, dim=32, heads=4, depth=1,
                          dtype=jnp2.float32, kv_heads=1)
    ds = next_token_dataset(rows.astype(np.int32))
    t = ADAG(spec, loss="sparse_softmax_cross_entropy",
             worker_optimizer="adam", learning_rate=5e-3, batch_size=64,
             communication_window=2, num_epoch=6, num_workers=2,
             label_col="label")
    params = t.train(ds)
    losses = t.get_history().losses()
    assert losses[-1] < losses[0] / 3
    out = generate(spec, params, rows[:4, :6].astype(np.int32),
                   max_new_tokens=8)
    expect = (rows[:4, :1] + np.arange(14)) % V
    assert (out == expect).mean() > 0.8


def test_gqa_validates_head_divisibility():
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=4,
                          depth=1, dtype=jnp.float32, kv_heads=3)
    with pytest.raises(ValueError, match="multiple of kv_heads"):
        spec.init_np(0)


def test_rope_decode_matches_full_forward():
    """RoPE LM: prefill + cached decode (cache holds pre-rotated keys)
    equals the full rotary forward at every position."""
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=DEPTH, dtype=jnp.float32,
                          pos_embedding="rope")
    params, _ = spec.init_np(0)
    rng = np.random.default_rng(4)
    toks = rng.integers(0, VOCAB, size=(2, 12)).astype(np.int32)
    _assert_cached_decode_matches_full(spec.module, params, toks, lp=4)


def test_rope_is_relative():
    """The defining RoPE property: rotating q and k at positions (p+s, p+s)
    gives the same attention scores as (p, p) — verify via apply_rope
    directly: <R(p+s)q, R(k+s)k> == <R(p)q, R(k)k> for aligned shifts."""
    from distkeras_tpu.models.lm import apply_rope, rope_angles

    rng = np.random.default_rng(5)
    dh, L, s = 16, 6, 9
    q = rng.normal(size=(1, L, 1, dh)).astype(np.float32)
    k = rng.normal(size=(1, L, 1, dh)).astype(np.float32)
    table = jnp.asarray(rope_angles(64, dh))
    q0, k0 = apply_rope(q, table[:L]), apply_rope(k, table[:L])
    qs, ks = apply_rope(q, table[s:s + L]), apply_rope(k, table[s:s + L])
    s0 = np.einsum("blhd,bmhd->blm", np.asarray(q0), np.asarray(k0))
    s1 = np.einsum("blhd,bmhd->blm", np.asarray(qs), np.asarray(ks))
    np.testing.assert_allclose(s1, s0, rtol=1e-4, atol=1e-4)


@pytest.mark.slow  # rope x gqa x window training composition; each part pinned separately in the fast tier
def test_rope_gqa_window_compose_and_train():
    """The modern-LM combo — RoPE + GQA + sliding window — trains through
    the trainer API and the cached decode continues the learned rule."""
    import jax.numpy as jnp2

    from distkeras_tpu.trainers import ADAG

    rng = np.random.default_rng(0)
    V, Lp1 = 32, 17
    start = rng.integers(0, V, size=(512, 1))
    rows = (start + np.arange(Lp1)) % V
    spec = transformer_lm(vocab=V, maxlen=64, dim=32, heads=4, depth=1,
                          dtype=jnp2.float32, kv_heads=2, attn_window=8,
                          pos_embedding="rope")
    ds = next_token_dataset(rows.astype(np.int32))
    t = ADAG(spec, loss="sparse_softmax_cross_entropy",
             worker_optimizer="adam", learning_rate=5e-3, batch_size=64,
             communication_window=2, num_epoch=6, num_workers=2,
             label_col="label")
    params = t.train(ds)
    losses = t.get_history().losses()
    assert losses[-1] < losses[0] / 3
    out = generate(spec, params, rows[:4, :6].astype(np.int32),
                   max_new_tokens=8)
    expect = (rows[:4, :1] + np.arange(14)) % V
    assert (out == expect).mean() > 0.8


def test_pos_embedding_validation():
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=1, dtype=jnp.float32, pos_embedding="learned")
    with pytest.raises(ValueError, match="pos_embedding"):
        spec.init_np(0)


def test_rope_requires_even_head_dim():
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=36, heads=4,
                          depth=1, dtype=jnp.float32, pos_embedding="rope")
    with pytest.raises(ValueError, match="even head dim"):
        spec.init_np(0)


def test_extend_matches_sequential_decode_steps(lm):
    """The multi-token cached forward (speculative decoding's verify pass)
    equals the same positions decoded one step at a time — logits and the
    caches it leaves behind."""
    spec, params = lm
    module = spec.module
    rng = np.random.default_rng(1)
    toks = rng.integers(0, VOCAB, size=(3, 11)).astype(np.int32)
    lp, T = 4, 5

    _, caches = module.apply(
        {"params": params}, toks[:, :lp], method=TransformerLM.prefill
    )
    ext_logits, ext_caches = module.apply(
        {"params": params}, toks[:, lp : lp + T], caches, lp,
        method=TransformerLM.extend,
    )
    step_caches = caches
    step_logits = []
    for pos in range(lp, lp + T):
        lg, step_caches = module.apply(
            {"params": params}, toks[:, pos], step_caches, pos,
            method=TransformerLM.decode_step,
        )
        step_logits.append(np.asarray(lg))
    np.testing.assert_allclose(
        np.asarray(ext_logits), np.stack(step_logits, axis=1),
        rtol=2e-4, atol=2e-4,
    )
    for (ka, va), (kb, vb) in zip(ext_caches, step_caches):
        np.testing.assert_allclose(np.asarray(ka), np.asarray(kb),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                   rtol=2e-4, atol=2e-4)


def test_speculative_matches_greedy_any_draft(lm):
    """Speculative output is EXACTLY the target's greedy stream no matter
    how bad the draft is — an unrelated random draft only costs rounds."""
    from distkeras_tpu.models import speculative_generate

    spec, params = lm
    draft = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=16, heads=2,
                           depth=1, dtype=jnp.float32)
    dparams, _ = draft.init_np(99)
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, VOCAB, size=(3, 6)).astype(np.int32)

    greedy = generate(spec, params, prompt, max_new_tokens=9)
    out, stats = speculative_generate(
        spec, params, draft, dparams, prompt, 9, spec_tokens=3
    )
    np.testing.assert_array_equal(out, greedy)
    assert stats["rounds"] >= 1
    # proposals are clamped to the emission budget: the final round may
    # overhang max_new_tokens, and those proposals don't count; stats are
    # per-row sums (B=3 rows, K=3)
    assert 0 < stats["proposed"] <= 3 * 3 * stats["rounds"]
    assert 0 <= stats["accepted"] <= stats["proposed"]
    assert 0.0 <= stats["acceptance"] <= 1.0


def test_speculative_self_draft_accepts_everything(lm):
    """With draft == target every proposal is accepted: K+1 tokens per
    verify pass, so rounds collapse ~(K+1)x vs one-at-a-time decode."""
    from distkeras_tpu.models import speculative_generate

    spec, params = lm
    prompt = np.ones((2, 5), np.int32)
    new, K = 12, 3
    greedy = generate(spec, params, prompt, max_new_tokens=new)
    out, stats = speculative_generate(
        spec, params, spec, params, prompt, new, spec_tokens=K
    )
    np.testing.assert_array_equal(out, greedy)
    assert stats["accepted"] == stats["proposed"]
    assert stats["acceptance"] == 1.0
    # 1 prefill token + rounds * (K+1) emissions must cover `new`
    assert stats["rounds"] == -(-(new - 1) // (K + 1))


@pytest.mark.slow  # spec x gqa x rope composition; spec exactness pin stays fast
def test_speculative_composes_with_gqa_and_rope():
    """The verify forward rides the same block machinery as decode — GQA
    cache layouts and RoPE offsets included."""
    from distkeras_tpu.models import speculative_generate

    spec = transformer_lm(vocab=32, maxlen=48, dim=32, heads=4, depth=2,
                          kv_heads=2, pos_embedding="rope",
                          dtype=jnp.float32)
    params, _ = spec.init_np(3)
    draft = transformer_lm(vocab=32, maxlen=48, dim=16, heads=2, depth=1,
                           kv_heads=1, pos_embedding="rope",
                           dtype=jnp.float32)
    dparams, _ = draft.init_np(4)
    prompt = np.arange(10, dtype=np.int32).reshape(2, 5) % 32

    greedy = generate(spec, params, prompt, max_new_tokens=8)
    out, _ = speculative_generate(
        spec, params, draft, dparams, prompt, 8, spec_tokens=4
    )
    np.testing.assert_array_equal(out, greedy)


def test_speculative_stats_clamped_to_budget(lm):
    """The final verify round's proposals that overhang max_new_tokens are
    excluded from proposed/accepted, so a perfect draft still reports
    acceptance == 1.0 (not >1 or a deflated proposed count)."""
    from distkeras_tpu.models import speculative_generate

    spec, params = lm
    prompt = np.ones((2, 5), np.int32)
    # new=9, K=4: with self-draft every round emits K+1=5, so the second
    # round overhangs (n=6, room=3) and only 3 of its 4 proposals count
    out, stats = speculative_generate(
        spec, params, spec, params, prompt, 9, spec_tokens=4
    )
    np.testing.assert_array_equal(
        out, generate(spec, params, prompt, max_new_tokens=9)
    )
    assert stats["rounds"] == 2
    # per-row sums over B=2 rows: each row proposes 4 + min(4, room=3)
    assert stats["proposed"] == 14
    assert stats["accepted"] == 14
    assert stats["acceptance"] == 1.0


def test_speculative_sampled_reproducible_and_valid(lm):
    """temperature>0 speculative decoding: same seed → same stream, tokens
    in-vocab, stats well-formed."""
    from distkeras_tpu.models import speculative_generate

    spec, params = lm
    draft = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=16, heads=2,
                           depth=1, dtype=jnp.float32)
    dparams, _ = draft.init_np(99)
    prompt = np.ones((3, 5), np.int32)
    a, sa = speculative_generate(spec, params, draft, dparams, prompt, 8,
                                 spec_tokens=3, temperature=1.0, seed=5)
    b, _ = speculative_generate(spec, params, draft, dparams, prompt, 8,
                                spec_tokens=3, temperature=1.0, seed=5)
    c, _ = speculative_generate(spec, params, draft, dparams, prompt, 8,
                                spec_tokens=3, temperature=1.0, seed=6)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert a.shape == (3, 13) and a.min() >= 0 and a.max() < VOCAB
    assert np.array_equal(a[:, :5], prompt)
    assert 0 <= sa["accepted"] <= sa["proposed"] <= 3 * 3 * sa["rounds"]


def test_speculative_sampled_topk1_degenerates_to_greedy(lm):
    """top_k=1 makes both warped distributions one-hot: any-temperature
    sampled speculation must emit exactly the target's greedy stream."""
    from distkeras_tpu.models import speculative_generate

    spec, params = lm
    draft = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=16, heads=2,
                           depth=1, dtype=jnp.float32)
    dparams, _ = draft.init_np(7)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, VOCAB, size=(2, 6)).astype(np.int32)
    greedy = generate(spec, params, prompt, max_new_tokens=9)
    out, _ = speculative_generate(spec, params, draft, dparams, prompt, 9,
                                  spec_tokens=3, temperature=2.0, top_k=1,
                                  seed=11)
    np.testing.assert_array_equal(out, greedy)


def test_speculative_sampled_self_draft_accepts_everything(lm):
    """draft == target ⇒ p == q at every position ⇒ min(1, p/q) == 1:
    acceptance is ~1.0. (Not asserted exact: q comes from decode_step and
    p from the extend verify pass — different XLA programs whose logits
    differ at f32 epsilon, and a top-k/top-p warp can flip a boundary
    token between the two truncated supports. Pure-temperature warps keep
    the ratio within e^±ε, so acceptance stays at 1.0 up to measure-zero
    draws; truncation makes the rare boundary rejection possible.)"""
    from distkeras_tpu.models import speculative_generate

    spec, params = lm
    prompt = np.ones((2, 5), np.int32)
    out, stats = speculative_generate(
        spec, params, spec, params, prompt, 12, spec_tokens=3,
        temperature=1.3, seed=2,
    )
    assert stats["acceptance"] >= 0.95
    assert out.shape == (2, 17) and out.max() < VOCAB
    # with the truncating warps, boundary flips may reject a token or two
    out2, stats2 = speculative_generate(
        spec, params, spec, params, prompt, 12, spec_tokens=3,
        temperature=1.3, top_k=8, top_p=0.9, seed=2,
    )
    assert stats2["acceptance"] >= 0.8
    assert out2.shape == (2, 17) and out2.max() < VOCAB


def test_speculative_sampled_preserves_target_distribution():
    """The Leviathan guarantee, measured: the token histogram of sampled
    speculative decoding matches plain sampled generate() on the same
    target (both draw from the identically-warped p). Aggregated over
    seeds × rows × positions; total-variation tolerance sized ~3× the
    expected sampling fluctuation at this n."""
    from distkeras_tpu.models import speculative_generate

    V = 16
    spec = transformer_lm(vocab=V, maxlen=16, dim=16, heads=2, depth=1,
                          dtype=jnp.float32)
    params, _ = spec.init_np(0)
    draft = transformer_lm(vocab=V, maxlen=16, dim=8, heads=2, depth=1,
                           dtype=jnp.float32)
    dparams, _ = draft.init_np(1)
    B, new, seeds = 64, 6, 12
    prompt = np.zeros((B, 2), np.int32)

    h_plain = np.zeros(V)
    h_spec = np.zeros(V)
    for s in range(seeds):
        g = generate(spec, params, prompt, new, temperature=1.5,
                     seed=1000 + s)
        h_plain += np.bincount(g[:, 2:].ravel(), minlength=V)
        o, _ = speculative_generate(spec, params, draft, dparams, prompt,
                                    new, spec_tokens=3, temperature=1.5,
                                    seed=2000 + s)
        h_spec += np.bincount(o[:, 2:].ravel(), minlength=V)
    n = h_plain.sum()
    assert n == h_spec.sum() == B * new * seeds
    tv = 0.5 * np.abs(h_plain / n - h_spec / n).sum()
    # expected TV between two empirical draws of p at n≈4600, V=16 is
    # ~0.02; 0.08 is a 3-4σ gate that still catches a wrong distribution
    # (e.g. greedy-biased acceptance shifts TV to ~0.3)
    assert tv < 0.08, f"token distributions diverge: TV={tv:.3f}"


@pytest.mark.slow  # sampled-spec x gqa x rope x warp composition; TV gate + reproducibility pins stay fast
def test_speculative_sampled_composes_with_gqa_rope_topk_topp():
    """Sampled verify rides the same block machinery: GQA caches, RoPE
    offsets, and the top-k/top-p warp all compose."""
    from distkeras_tpu.models import speculative_generate

    spec = transformer_lm(vocab=32, maxlen=48, dim=32, heads=4, depth=2,
                          kv_heads=2, pos_embedding="rope",
                          dtype=jnp.float32)
    params, _ = spec.init_np(3)
    draft = transformer_lm(vocab=32, maxlen=48, dim=16, heads=2, depth=1,
                           kv_heads=1, pos_embedding="rope",
                           dtype=jnp.float32)
    dparams, _ = draft.init_np(4)
    prompt = np.arange(10, dtype=np.int32).reshape(2, 5) % 32
    out, stats = speculative_generate(
        spec, params, draft, dparams, prompt, 8, spec_tokens=4,
        temperature=0.8, top_k=12, top_p=0.95, seed=1,
    )
    assert out.shape == (2, 13) and out.max() < 32
    assert 0.0 <= stats["acceptance"] <= 1.0


def test_speculative_sampled_validates_inputs(lm):
    from distkeras_tpu.models import speculative_generate

    spec, params = lm
    prompt = np.zeros((1, 4), np.int32)
    with pytest.raises(ValueError, match="temperature"):
        speculative_generate(spec, params, spec, params, prompt, 4,
                             temperature=-1.0)
    with pytest.raises(ValueError, match="top_k"):
        speculative_generate(spec, params, spec, params, prompt, 4,
                             temperature=1.0, top_k=0)
    with pytest.raises(ValueError, match="top_p"):
        speculative_generate(spec, params, spec, params, prompt, 4,
                             temperature=1.0, top_p=1.5)


def test_speculative_validates_inputs(lm):
    from distkeras_tpu.models import speculative_generate

    spec, params = lm
    prompt = np.zeros((1, 4), np.int32)
    other_vocab = transformer_lm(vocab=VOCAB * 2, maxlen=MAXLEN, dim=16,
                                 heads=2, depth=1, dtype=jnp.float32)
    ov_params, _ = other_vocab.init_np(0)
    with pytest.raises(ValueError, match="vocab"):
        speculative_generate(spec, params, other_vocab, ov_params,
                             prompt, 4)
    windowed = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=16, heads=2,
                              depth=1, attn_window=8, dtype=jnp.float32)
    w_params, _ = windowed.init_np(0)
    with pytest.raises(ValueError, match="sliding-window"):
        speculative_generate(windowed, w_params, windowed, w_params,
                             prompt, 4)
    with pytest.raises(ValueError, match="spec_tokens"):
        speculative_generate(spec, params, spec, params, prompt, 4,
                             spec_tokens=0)
    with pytest.raises(ValueError, match="maxlen"):
        # fits generate()'s bound but not the verify probe's headroom
        speculative_generate(spec, params, spec, params,
                             np.zeros((1, MAXLEN - 6), np.int32), 6,
                             spec_tokens=4)
    with pytest.raises(TypeError, match="draft"):
        from distkeras_tpu.models import mlp

        speculative_generate(spec, params, mlp(), params, prompt, 4)


@pytest.mark.slow  # long-wrap stress; prompt-longer-than-window ring pin stays fast
def test_ring_cache_shape_and_long_wraparound():
    """Sliding-window LM decode uses a RING cache of length window (not
    maxlen), and stays equal to the full windowed forward far past the
    first wrap-around (decode length >> window), composed with GQA+RoPE."""
    W = 5
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=DEPTH, dtype=jnp.float32, attn_window=W,
                          kv_heads=2, pos_embedding="rope")
    params, _ = spec.init_np(0)
    module = spec.module
    rng = np.random.default_rng(6)
    toks = rng.integers(0, VOCAB, size=(2, 28)).astype(np.int32)

    _, caches = module.apply(
        {"params": params}, toks[:, :3], method=TransformerLM.prefill
    )
    kc, vc = caches[0]
    assert kc.shape == (2, W, 2, DIM // HEADS)   # ring: window, not maxlen
    # 25 steps = 5 full wraps
    _assert_cached_decode_matches_full(module, params, toks, lp=3)


def test_ring_cache_prompt_longer_than_window():
    """Prefill with a prompt LONGER than the window seeds the ring with the
    last `window` positions only; decode continues exactly."""
    W = 4
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=1, dtype=jnp.float32, attn_window=W)
    params, _ = spec.init_np(0)
    rng = np.random.default_rng(7)
    toks = rng.integers(0, VOCAB, size=(2, 16)).astype(np.int32)
    # prompt (11) >> window (4); skip the prefill-logits check — it's the
    # ring seeding + continued decode under test here
    _assert_cached_decode_matches_full(spec.module, params, toks, lp=11,
                                       check_prefill_logits=False)


# -- beam search --------------------------------------------------------------


def _seq_logprob(spec, params, seq, lp):
    """Sum of log P(seq[t] | seq[:t]) for t >= lp, by full forward."""
    logits = spec.apply(params, {}, jnp.asarray(seq[None], jnp.int32),
                        training=False)[0][0]
    logprobs = jax.nn.log_softmax(np.asarray(logits, np.float32), axis=-1)
    return float(sum(
        logprobs[t - 1, seq[t]] for t in range(lp, len(seq))
    ))


def test_beam_one_equals_greedy(lm):
    from distkeras_tpu.models import beam_search

    spec, params = lm
    prompt = np.arange(8, dtype=np.int32).reshape(2, 4) % VOCAB
    greedy = generate(spec, params, prompt, max_new_tokens=6)
    toks, scores = beam_search(spec, params, prompt, max_new_tokens=6,
                               beams=1)
    assert toks.shape == (2, 1, 10)
    assert scores.shape == (2, 1)
    np.testing.assert_array_equal(toks[:, 0], greedy)


def test_beam_search_finds_higher_likelihood_than_greedy(lm):
    from distkeras_tpu.models import beam_search

    spec, params = lm
    prompt = np.array([[3, 1, 4, 1], [5, 9, 2, 6]], np.int32)
    new = 8
    greedy = generate(spec, params, prompt, max_new_tokens=new)
    toks, scores = beam_search(spec, params, prompt, max_new_tokens=new,
                               beams=4)
    for b in range(2):
        lp = prompt.shape[1]
        best = _seq_logprob(spec, params, toks[b, 0], lp)
        base = _seq_logprob(spec, params, greedy[b], lp)
        # the reported score must BE the sequence log-prob (this is the
        # oracle that catches a wrong parent-cache re-gather: a corrupted
        # cache changes the decode distribution, and the rescore diverges)
        np.testing.assert_allclose(scores[b, 0], best, rtol=1e-4, atol=1e-3)
        # beam-4 improving on greedy is NOT a theorem (the greedy path can
        # fall out of the beam), but it holds for this pinned fixture
        assert best >= base - 1e-4
        # beams come back best-first
        assert np.all(np.diff(scores[b]) <= 1e-6)


def test_beam_search_eos_freezes_finished_beams(lm):
    from distkeras_tpu.models import beam_search

    spec, params = lm
    prompt = np.array([[7, 7, 7, 7]], np.int32)
    eos = 5
    toks, scores = beam_search(spec, params, prompt, max_new_tokens=10,
                               beams=4, eos_id=eos)
    lp = prompt.shape[1]
    for k in range(4):
        seq = toks[0, k, lp:]
        hit = np.where(seq == eos)[0]
        if len(hit):
            # everything after the first eos is eos padding
            assert np.all(seq[hit[0]:] == eos)
    assert np.all(np.isfinite(scores))


def test_beam_search_length_penalty_and_validation(lm):
    from distkeras_tpu.models import beam_search

    spec, params = lm
    prompt = np.zeros((1, 4), np.int32)
    toks, scores = beam_search(spec, params, prompt, max_new_tokens=5,
                               beams=3, length_penalty=0.8, eos_id=2)
    assert toks.shape == (1, 3, 9)
    with pytest.raises(ValueError, match="beams"):
        beam_search(spec, params, prompt, max_new_tokens=2, beams=0)
    with pytest.raises(ValueError, match="eos_id"):
        beam_search(spec, params, prompt, max_new_tokens=2, eos_id=VOCAB)
    with pytest.raises(ValueError, match="maxlen"):
        beam_search(spec, params, prompt, max_new_tokens=MAXLEN)


@pytest.mark.slow  # beam x ring x gqa composition; beam-vs-greedy pin stays fast
def test_beam_search_with_ring_cache_and_gqa():
    """Beam search composes with the RoPE + GQA + sliding-window dialect:
    the per-beam caches are ring buffers and the parent re-gather must
    respect them."""
    from distkeras_tpu.models import beam_search

    spec = transformer_lm(vocab=32, maxlen=64, dim=32, heads=4, depth=2,
                          dtype=jnp.float32, kv_heads=2, attn_window=8,
                          pos_embedding="rope")
    params, _ = spec.init_np(1)
    prompt = np.arange(12, dtype=np.int32).reshape(1, 12) % 32
    toks, scores = beam_search(spec, params, prompt, max_new_tokens=16,
                               beams=3)
    assert toks.shape == (1, 3, 28)
    assert np.all(toks < 32) and np.all(toks >= 0)
    lp = prompt.shape[1]
    # every beam's reported score must match the full windowed forward's
    # log-prob of that sequence — a wrong ring-slot re-gather after a beam
    # switch would corrupt the decode distribution and break this (the
    # tolerance absorbs the pinned 2e-4/step cached-vs-full f32 noise
    # accumulated over 16 steps)
    for k in range(3):
        rescored = _seq_logprob(spec, params, toks[0, k], lp)
        np.testing.assert_allclose(scores[0, k], rescored, atol=5e-2)
    # distinct hypotheses, best-first
    assert len({tuple(t) for t in toks[0]}) == 3
    assert np.all(np.diff(scores[0]) <= 1e-6)


def test_generator_predictor_beam_mode(lm):
    """beams>1 routes through beam_search and keeps each row's best beam;
    sampling knobs are rejected in beam mode."""
    from distkeras_tpu.data import Dataset
    from distkeras_tpu.models import beam_search
    from distkeras_tpu.predictors import GeneratorPredictor

    spec, params = lm
    rng = np.random.default_rng(3)
    prompts = rng.integers(0, VOCAB, size=(7, 5)).astype(np.int32)
    ds = Dataset({"features": prompts})
    p = GeneratorPredictor(spec, params, max_new_tokens=4, batch_size=4,
                           beams=3)
    out = p.predict(ds)
    assert out["generated"].shape == (7, 4)
    # chunked predictor output == direct best-beam on the same rows
    direct, _ = beam_search(spec, params, prompts[:4], max_new_tokens=4,
                            beams=3)
    np.testing.assert_array_equal(out["generated"][:4], direct[:, 0, 5:])

    with pytest.raises(ValueError, match="deterministic"):
        GeneratorPredictor(spec, params, beams=2, temperature=0.5)
    with pytest.raises(ValueError, match="beams"):
        GeneratorPredictor(spec, params, beams=0)


# -- weight tying -------------------------------------------------------------


def test_tied_embeddings_structure_and_logits():
    """tie_embeddings drops lm_head from the params tree and computes
    logits as hidden @ embedding.T (nn.Embed.attend)."""
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=1, dtype=jnp.float32, tie_embeddings=True)
    params, _ = spec.init_np(0)
    assert "lm_head" not in params
    assert params["embed"]["embedding"].shape == (VOCAB, DIM)
    toks = np.arange(8, dtype=np.int32).reshape(1, 8)
    logits = spec.apply(params, {}, jnp.asarray(toks), False)[0]
    h = spec.module.apply({"params": params}, jnp.asarray(toks),
                          method=TransformerLM.hidden)
    manual = np.asarray(h) @ np.asarray(params["embed"]["embedding"]).T
    np.testing.assert_allclose(np.asarray(logits), manual, rtol=1e-5,
                               atol=1e-5)


@pytest.mark.slow  # tied x fused-ce composition; each pinned separately in the fast tier
def test_tied_fused_ce_matches_unfused():
    """fused_ce on a tied model contracts against the embedding transpose —
    loss and gradients equal the unfused tied path."""
    from distkeras_tpu.ops.losses import get_loss
    from distkeras_tpu.trainers import _make_loss_step

    cfg = dict(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS, depth=1,
               dtype=jnp.float32, tie_embeddings=True)
    plain = transformer_lm(**cfg)
    fused = transformer_lm(**cfg, fused_ce=True, ce_chunk=8)
    params, _ = plain.init_np(0)
    rng = np.random.default_rng(4)
    toks = rng.integers(0, VOCAB, size=(3, 17)).astype(np.int32)
    batch = (toks[:, :-1], toks[:, 1:])
    name = "sparse_softmax_cross_entropy"
    sp = _make_loss_step(plain, get_loss(name), 1, loss_name=name)
    sf = _make_loss_step(fused, get_loss(name), 1, loss_name=name)
    (lp, _), gp = jax.value_and_grad(sp, has_aux=True)(params, {}, batch)
    (lf, _), gf = jax.value_and_grad(sf, has_aux=True)(params, {}, batch)
    np.testing.assert_allclose(float(lf), float(lp), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-6)


@pytest.mark.slow  # tied train+generate+quantize integration; tied structure/logits pin stays fast
def test_tied_lm_trains_generates_and_quantizes():
    """End to end on the cycle language: the tied model (V·dim fewer
    params) learns, decodes the cycle, beam-decodes it, and survives int8
    quantization (blocks quantized; the tied head stays in the trained
    dtype)."""
    from distkeras_tpu import ADAG
    from distkeras_tpu.models import beam_search, quantize_lm

    period = 8
    rng = np.random.default_rng(0)
    rows = np.stack([
        (np.arange(17) + s) % period for s in rng.integers(0, period, 512)
    ]).astype(np.int32)
    spec = transformer_lm(vocab=period, maxlen=32, dim=32, heads=4, depth=2,
                          dtype=jnp.float32, tie_embeddings=True)
    t = ADAG(spec, loss="sparse_softmax_cross_entropy",
             worker_optimizer="adam", learning_rate=5e-3, num_workers=4,
             batch_size=32, communication_window=2, num_epoch=6)
    t.train(next_token_dataset(rows), shuffle=True)
    params = t.trained_params_
    prompt = np.tile(np.arange(6) % period, (2, 1)).astype(np.int32)
    out = generate(spec, params, prompt, max_new_tokens=8)
    expect = (np.arange(6, 14) % period)[None].repeat(2, axis=0)
    assert np.array_equal(out[:, 6:], expect)
    btoks, _ = beam_search(spec, params, prompt, max_new_tokens=8, beams=3)
    assert np.array_equal(btoks[:, 0, 6:], expect)
    qspec, qparams = quantize_lm(spec, params)
    assert "lm_head" not in qparams
    qout = generate(qspec, qparams, prompt, max_new_tokens=8)
    assert np.array_equal(qout[:, 6:], expect)
