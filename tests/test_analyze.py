"""The analyst (ISSUE 14): interval algebra on hand-built span streams
(known critical paths, overlap fractions, lock-wait attribution, the
degraded verdict on dropped spans), the Perfetto counter-track and
gzip/rotation satellites, the analyze CLI, the BottleneckShiftRule, and
the end-to-end acceptance runs — a seeded straggler is NAMED, a
per-record-fsync durable run classifies fsync-bound while the
group-commit window does not, and a pipelined run's overlap fraction
matches the serial/pipelined oracle."""

import json
import os
import time

import pytest

import distkeras_tpu as dk
from distkeras_tpu.observability import analyze as an
from distkeras_tpu.observability import trace
from distkeras_tpu.observability.timeseries import TimeSeriesStore
from tests.test_trainers import blobs_dataset, model_spec

MS = 1_000_000  # ns per ms


def ev(name, t0_ms, dur_ms, corr=None, tid=1, cat="", args=None):
    return {"name": name, "cat": cat, "corr": corr,
            "t0_ns": int(t0_ms * MS), "dur_ns": int(dur_ms * MS),
            "tid": tid, "tname": f"t{tid}", "args": args}


def serial_window(base_ms, wid=0, n=1, compute_ms=5.0, wire_ms=1.5,
                  decode_ms=1.0, lock_ms=1.5, fold_ms=2.0,
                  append_ms=1.0, wait_ms=3.0):
    """One serial-loop window's spans: compute/fetch, compress, commit
    with a corr-stitched server-side decomposition. Returns (events,
    end_ms)."""
    xc, sc = f"w{wid}:x{n}", f"w{wid}:s{n}"
    t = base_ms
    evs = [
        ev("worker.compute", t - 0.5, compute_ms + 0.5, corr=xc,
           tid=10 + wid),
        ev("worker.fetch", t, compute_ms, corr=xc, tid=10 + wid),
        ev("worker.compress", t + compute_ms, 1.0, corr=xc, tid=10 + wid),
    ]
    c0 = t + compute_ms + 1.0
    commit = decode_ms + lock_ms + fold_ms + append_ms + wait_ms + wire_ms
    evs.append(ev("worker.commit", c0, commit, corr=sc, tid=10 + wid))
    s = c0 + wire_ms / 2
    evs.append(ev("ps.decode", s, decode_ms, corr=sc, tid=99))
    s += decode_ms + lock_ms                   # the decode→fold gap
    evs.append(ev("ps.fold", s, fold_ms, corr=sc, tid=99))
    s += fold_ms
    evs.append(ev("ps.wal_append", s, append_ms, corr=sc, tid=99))
    s += append_ms
    evs.append(ev("ps.wal_wait", s, wait_ms, corr=sc, tid=99))
    return evs, c0 + commit


# -- interval algebra ---------------------------------------------------------


def test_interval_primitives():
    assert an.merge_intervals([(5, 7), (0, 3), (2, 4)]) == [(0, 4), (5, 7)]
    assert an.union_length([(0, 10), (5, 15), (20, 21)]) == 16
    assert an.intersect_intervals([(0, 10)], [(5, 20), (25, 30)]) \
        == [(5, 10)]
    assert an._subtract([(0, 10)], [(2, 4), (6, 20)]) == [(0, 2), (4, 6)]
    assert an._subtract([(0, 5)], []) == [(0, 5)]


def test_regime_code_roundtrip():
    for i, name in enumerate(an.REGIMES):
        assert an.regime_code(name) == i


# -- window assembly + waterfall ---------------------------------------------


def test_serial_waterfall_decomposition():
    evs, _ = serial_window(100.0, wid=0, n=1)
    rep = an.analyze_events(evs, host_cores=8)
    tr = rep["training"]
    assert tr["windows"] == 1
    w = tr["workers"]["0"]
    assert w["windows"] == 1
    # known critical path: each phase lands in its own bucket
    assert w["compute_ms"] == pytest.approx(5.5, abs=0.01)
    assert w["decode_ms"] == pytest.approx(1.0, abs=0.01)
    assert w["lock_wait_ms"] == pytest.approx(1.5, abs=0.01)
    assert w["fold_ms"] == pytest.approx(2.0, abs=0.01)
    assert w["wal_ms"] == pytest.approx(4.0, abs=0.01)   # append + wait
    assert w["wire_ms"] == pytest.approx(1.5, abs=0.01)
    # nothing hidden in a serial stream
    assert tr["overlap"]["fraction"] == 0.0
    assert rep["degraded"] is False and rep["dropped_spans"] == 0


def test_lock_wait_attributed_to_the_worker_that_waited():
    evs = []
    e, _ = serial_window(0.0, wid=0, n=1, lock_ms=0.1)
    evs += e
    e, _ = serial_window(0.0, wid=1, n=1, lock_ms=40.0)  # queued behind 0
    evs += e
    tr = an.analyze_events(evs, host_cores=8)["training"]
    assert tr["workers"]["1"]["lock_wait_ms"] == pytest.approx(40.0,
                                                               rel=0.01)
    assert tr["workers"]["0"]["lock_wait_ms"] == pytest.approx(0.1,
                                                               abs=0.05)


def test_fold_lock_regime_on_hand_built_stream():
    evs = []
    t = 0.0
    for n in range(1, 5):
        e, t = serial_window(t + 0.5, wid=0, n=n, compute_ms=1.0,
                             lock_ms=30.0, fold_ms=10.0, wire_ms=0.5,
                             wait_ms=0.2, append_ms=0.2, decode_ms=0.3)
        evs += e
    rep = an.analyze_events(evs, host_cores=8)
    assert rep["verdict"]["regime"] == "fold-lock-bound"


def test_fsync_regime_on_hand_built_stream():
    evs = []
    t = 0.0
    for n in range(1, 5):
        e, t = serial_window(t + 0.5, wid=0, n=n, compute_ms=1.0,
                             wait_ms=25.0, append_ms=5.0, lock_ms=0.2,
                             fold_ms=0.5, wire_ms=0.5, decode_ms=0.2)
        evs += e
    rep = an.analyze_events(evs, host_cores=8)
    assert rep["verdict"]["regime"] == "fsync-bound"
    assert any("ps_wal_group_window" in r
               for r in rep["verdict"]["recommendations"])


def test_overlap_fully_hidden_pipelined_stream():
    """Pipelined shape: window N's commit runs inside window N+1's
    dispatch→fetch-return span and the fetch still waits afterwards →
    the exchange is hidden (overlap ~1.0) and charged nothing."""
    evs = []
    # window 1: fetch [10,18]; its commit [21,25] hides under window
    # 2's compute [20,40] (dispatch at 20); window 2's fetch [25,40]
    # still waits 15ms → device-critical
    evs.append(ev("worker.compute", 2, 16, corr="w0:x1", tid=10))
    evs.append(ev("worker.fetch", 10, 8, corr="w0:x1", tid=10))
    evs.append(ev("worker.compress", 18, 1, corr="w0:x1", tid=10))
    evs.append(ev("worker.compute", 20, 20, corr="w0:x2", tid=10))
    evs.append(ev("worker.commit", 21, 4, corr="w0:s1", tid=10))
    evs.append(ev("worker.fetch", 25, 15, corr="w0:x2", tid=10))
    evs.append(ev("worker.compress", 40, 1, corr="w0:x2", tid=10))
    evs.append(ev("worker.commit", 41.5, 4, corr="w0:s2", tid=10))
    rep = an.analyze_events(evs, host_cores=8)
    tr = rep["training"]
    # commit 1 hidden (4ms of 8ms total exchange)
    assert tr["overlap"]["fraction"] == pytest.approx(0.5, abs=0.01)
    # the hidden, device-critical exchange is charged nothing: worker
    # wire total is only window 2's EXPOSED commit
    assert tr["workers"]["0"]["wire_ms"] == pytest.approx(4.0, abs=0.1)


def test_hidden_but_exchange_critical_window_is_charged():
    """Hidden commit whose following fetch returned immediately: the
    exchange was the constraint — its decomposition IS charged and the
    enveloping window only counts its fetch residue as compute."""
    evs = [
        ev("worker.compute", 2, 6, corr="w0:x1", tid=10),
        ev("worker.fetch", 4, 4, corr="w0:x1", tid=10),
        ev("worker.compress", 8, 0.5, corr="w0:x1", tid=10),
        # window 2 dispatched at 9; commit of window 1 runs [9.5, 29.5]
        ev("worker.compute", 9, 21, corr="w0:x2", tid=10),
        ev("worker.commit", 9.5, 20, corr="w0:s1", tid=10),
        # fetch residue ~0: the device finished long before the wire did
        ev("worker.fetch", 29.96, 0.04, corr="w0:x2", tid=10),
        ev("worker.compress", 30.0, 0.5, corr="w0:x2", tid=10),
        ev("worker.commit", 30.5, 20, corr="w0:s2", tid=10),
    ]
    tr = an.analyze_events(evs, host_cores=8)["training"]
    w = tr["workers"]["0"]
    # both commits charged as wire (no server spans): 40ms total
    assert w["wire_ms"] == pytest.approx(40.0, rel=0.05)
    # window 2's compute evidence is its ~0 fetch residue, not the 21ms
    # span that merely enveloped window 1's exchange
    assert w["compute_ms"] < 15.0


def test_dropped_spans_degrade_never_invent(tmp_path):
    evs, _ = serial_window(0.0, wid=0, n=1)
    # a commit whose fetch anchor was dropped: skipped, not guessed
    orphan = ev("worker.commit", 500.0, 4.0, corr="w3:s9", tid=13)
    rep = an.analyze_events(evs + [orphan], dropped=7, host_cores=8)
    assert rep["degraded"] is True
    assert rep["verdict"]["degraded"] is True
    assert rep["dropped_spans"] == 7
    assert rep["skipped_windows"] >= 1
    assert "3" not in rep["training"]["workers"]
    assert any("dropped" in r.lower()
               for r in rep["verdict"]["recommendations"])
    # rc contract: the CLI exits 2 on a degraded verdict
    trace.enable(ring_size=4096)
    try:
        for e in evs:
            trace.record(e["name"], e["t0_ns"], e["t0_ns"] + e["dur_ns"],
                         corr=e["corr"])
        path = trace.save(str(tmp_path / "t.json"))
    finally:
        trace.disable()
    from distkeras_tpu.observability.__main__ import main
    assert main(["analyze", path]) == 0


def test_host_core_bound_classification():
    totals = {"compute": 900.0, "compress": 0.0, "wire": 10.0,
              "decode": 0.0, "lock_wait": 0.0, "fold": 5.0, "wal": 5.0}
    regime, _ = an.classify(totals, host_cores=1, n_workers=4,
                            wall_ms=500.0, busy_ms=950.0)
    assert regime == "host-core-bound"
    # ample cores: plain compute-bound
    regime2, _ = an.classify(totals, host_cores=64, n_workers=4,
                             wall_ms=500.0, busy_ms=950.0)
    assert regime2 == "compute-bound"
    assert an.classify({}, host_cores=1)[0] == "idle"


def test_serving_report_and_queue_regime():
    evs = [
        ev("serve.request", 0, 100, corr="r1", tid=5,
           args={"state": "done"}),
        ev("serve.queued", 0, 70, corr="r1", tid=5),
        ev("serve.prefill", 70, 10, corr="r1", tid=5),
        ev("serve.request", 5, 95, corr="r2", tid=5,
           args={"state": "done"}),
        ev("serve.queued", 5, 60, corr="r2", tid=5),
        ev("serve.decode_step", 80, 5, tid=5, args={"rows": 4,
                                                    "batch": 4}),
        ev("serve.decode_step", 85, 15, tid=5, args={"rows": 8}),
    ]
    rep = an.analyze_events(evs, host_cores=8)
    sv = rep["serving"]
    assert sv["requests"] == 2 and sv["dominant"] == "queue"
    # duration-weighted rows: (4*5 + 8*15) / 20
    assert sv["mean_rows_in_flight"] == pytest.approx(7.0)
    assert rep["verdict"]["regime"] == "queue-bound"
    assert any("admission" in r
               for r in rep["verdict"]["recommendations"])


def test_convoyed_lock_waits_do_not_eclipse_wire():
    """Review regression: four workers convoyed on the center lock for
    the SAME 100 ms stretch, each with ~90 ms of genuine wire — the
    classifier must union the shared lock stretch (100 ms, once), not
    subtract the 400 ms per-worker sum from the wire bucket."""
    evs = []
    for wid in range(4):
        sc = f"w{wid}:s1"
        evs += [
            ev("worker.compute", 0.5 + wid, 2.0, corr=f"w{wid}:x1",
               tid=10 + wid),
            ev("worker.fetch", 1 + wid, 1.5, corr=f"w{wid}:x1",
               tid=10 + wid),
            ev("worker.compress", 2.5 + wid, 0.5, corr=f"w{wid}:x1",
               tid=10 + wid),
            # commit spans [5, 200]: decode 2ms, a ~50ms lock wait on
            # the SHARED wall stretch [·, 60], fold 1ms, the rest wire
            ev("worker.commit", 5 + wid, 195, corr=sc, tid=10 + wid),
            ev("ps.decode", 6 + wid, 2, corr=sc, tid=99),
            ev("ps.fold", 60, 1, corr=sc, tid=99),
        ]
    rep = an.analyze_events(evs, host_cores=8)
    tr = rep["training"]
    # per-worker sums still say who waited ~50 ms each (~200 summed)
    assert tr["totals_ms"]["lock_wait"] == pytest.approx(200, rel=0.1)
    # but the classifier sees ONE ~50 ms lock stretch vs ~140 ms wire
    # (the old sum-subtraction zeroed wire entirely: 190 - 200 < 0)
    assert tr["union_ms"]["lock_wait"] == pytest.approx(52, rel=0.1)
    assert rep["verdict"]["regime"] == "wire-bound", \
        rep["verdict"]["fractions"]


def test_two_worker_straggler_is_still_named():
    """Review regression: with exactly two workers the (upper) median
    was the straggler's own cadence/stall, so it could never exceed
    2× itself — the lower median keeps the smallest pool honest."""
    evs = []
    t0, t1 = 0.0, 0.0
    for n in range(1, 5):
        e, t0 = serial_window(t0 + 1.0, wid=0, n=n)
        evs += e
        e, t1 = serial_window(t1 + 200.0, wid=1, n=n)  # 200ms stalls
        evs += e
    tr = an.analyze_events(evs, host_cores=8)["training"]
    assert tr["dominant_wait_worker"] == 1
    assert tr["stragglers"] == [1]


def test_regime_tracker_end_cursor_keeps_long_spans():
    """Review regression: spans land in the ring at CLOSE, so a
    start-time cursor would permanently drop a long compute span whose
    dispatch predates short commit spans an earlier tick consumed —
    classifying a 2 s-compute / 30 ms-wire pipelined run as wire-bound
    forever. The end-time cursor keeps it compute-bound."""
    store = TimeSeriesStore()
    tracker = an.RegimeTracker()
    # tick 1 sees only the short spans that closed mid-window (the
    # compute span is still open): commit + fold of the previous window
    tick1 = [
        ev("worker.commit", 100, 30, corr="w0:s1", tid=10),
        ev("ps.fold", 115, 2, corr="w0:s1", tid=10),
    ]
    tracker.observe(tick1, store, 1.0)
    # tick 2 delivers the 2000 ms compute span that closed AFTER tick 1
    # — its t0 (0) predates everything already observed
    tick2 = tick1 + [
        ev("worker.compute", 0, 2000, corr="w0:x2", tid=10),
        ev("worker.fetch", 1900, 100, corr="w0:x2", tid=10),
    ]
    tracker.observe(
        [e for e in tick2 if e["t0_ns"] + e["dur_ns"] > tracker._cursor],
        store, 2.0)
    codes = [v for _, v in store.get("analyze.regime_code").points()]
    assert codes[-1] == an.regime_code("compute-bound"), codes


def test_elastic_pull_before_fetch_keeps_stall_and_is_not_double_charged():
    """Review regression: the elastic (EASGD) loop pulls BEFORE its
    window's fetch, so the pull span attaches to the previous window —
    it must neither extend that window's end (erasing the straggler's
    boundary stall) nor be charged on top of the compute span that
    envelops it."""
    def window(base, n, wid=0):
        xc = f"w{wid}:x{n}"
        return [
            # dispatch at base; pull rides INSIDE the compute span
            ev("worker.compute", base, 14, corr=xc, tid=10),
            ev("worker.pull", base + 0.5, 3, corr=xc, tid=10),
            ev("worker.fetch", base + 4, 10, corr=xc, tid=10),
            ev("worker.compress", base + 14, 1, corr=xc, tid=10),
            ev("worker.commit", base + 15, 4, corr=xc, tid=10),
        ]

    evs = []
    base = 0.0
    for n in range(1, 4):
        evs += window(base, n)
        base += 19 + 200.0          # 200 ms boundary sleep per window
    tr = an.analyze_events(evs, host_cores=8)["training"]
    w = tr["workers"]["0"]
    # the boundary sleeps survive as stall (2 gaps × 200 ms)...
    assert w["stall_ms"] == pytest.approx(400.0, rel=0.05)
    # ...and the compute-enveloped pulls are charged nothing (the
    # dispatch→fetch-return span already covers that wall)
    assert w["pull_ms"] == 0.0
    # the overlap metric agrees with the charging rule: hidden pulls
    # count as hidden exchange even though the commits stay exposed.
    # Window N's pull precedes its fetch anchor so it attaches to
    # window N-1 (the first one, before any anchor, is dropped): 2
    # hidden pulls × 3 ms over 3 commits × 4 ms + 2 pulls × 3 ms = 1/3.
    assert tr["overlap"]["fraction"] == pytest.approx(1 / 3, abs=0.02)


def test_regime_tracker_accumulates_subthreshold_evidence():
    """Review regression: sub-threshold fresh spans must stay
    unconsumed (the cursor holds) so sparse runs accumulate evidence
    across ticks instead of shedding it and never sampling."""
    store = TimeSeriesStore()
    tracker = an.RegimeTracker(min_span_ms=1.0)
    # 0.4 ms of compute per tick: below threshold alone, ample in three
    drip = []
    for i in range(3):
        drip.append(ev("worker.fetch", i * 10, 0.4, corr="w0:x1",
                       tid=10))
        tracker.observe([e for e in drip
                         if e["t0_ns"] + e["dur_ns"] > tracker._cursor],
                        store, float(i))
    s = store.get("analyze.regime_code")
    assert s is not None and len(s) == 1     # sampled once, on tick 3
    assert [v for _, v in s.points()] == [an.regime_code("compute-bound")]


def test_regime_code_series_never_averages_codes():
    """Review regression: the code series is categorical — ring
    downsampling must keep true observed codes (counter semantics),
    never average 0 and 2 into a phantom wire-bound 1."""
    store = TimeSeriesStore(capacity=16)
    tracker = an.RegimeTracker(min_span_ms=0.1)
    for i in range(40):   # force several downsample passes
        name = ("worker.fetch" if i % 2 == 0 else "ps.wal_wait")
        evs = [ev(name, i * 100, 5, corr="w0:x1", tid=10),
               ev("wal.fsync" if i % 2 else "worker.fetch",
                  i * 100 + 6, 5, corr=None if i % 2 else "w0:x1",
                  tid=20)]
        tracker.observe(evs, store, float(i))
    s = store.get("analyze.regime_code")
    assert s is not None and s.kind == "counter"
    codes = {v for _, v in s.points()}
    valid = {float(an.regime_code(r)) for r in an.REGIMES}
    assert codes <= valid, codes


def test_union_accounting_counts_shared_waits_once():
    """Four workers waiting on the SAME group fsync cost the run one
    fsync of wall, not four — the classifier's union accounting."""
    evs = []
    for wid in range(4):
        e, _ = serial_window(0.0, wid=wid, n=1, compute_ms=30.0,
                             wait_ms=0.0, append_ms=0.0, lock_ms=0.0,
                             fold_ms=0.1, wire_ms=0.4, decode_ms=0.1)
        evs += e
        # every worker waits the same wall interval [40, 60] — all four
        # convoyed behind ONE flusher fsync covering the same stretch
        evs.append(ev("ps.wal_wait", 40.0, 20.0, corr=f"w{wid}:s1",
                      tid=99 + wid))
    evs.append(ev("wal.fsync", 40.0, 20.0, tid=200))
    rep = an.analyze_events(evs, host_cores=8)
    # per-worker sums see 20ms of durability wait each...
    assert rep["training"]["totals_ms"]["wal"] == pytest.approx(
        80.0, rel=0.05)
    # ...but the union (the classifier's input) counts the log device's
    # ONE fsync once
    assert rep["training"]["union_ms"]["wal"] == pytest.approx(
        20.0, rel=0.05)
    assert rep["verdict"]["regime"] == "compute-bound"


# -- satellites: counter tracks, gzip, rotation -------------------------------


def test_counter_tracks_save_load_roundtrip(tmp_path):
    trace.enable(ring_size=4096)
    try:
        with trace.span("ps.fold"):
            time.sleep(0.001)
        trace.counter("ps.tau_p95", 3.5)
        trace.counter("ps.tau_p95", 7.25)
        trace.counter("serve.rows_in_flight", 4)
        path = trace.save(str(tmp_path / "trace.json"))
    finally:
        trace.disable()
    doc = json.loads(open(path).read())
    counters = [r for r in doc["traceEvents"] if r.get("ph") == "C"]
    assert len(counters) == 3
    assert counters[0]["args"] == {"value": 3.5}
    assert doc["otherData"]["host_cores"] == (os.cpu_count() or 1)
    events, meta = an.load_trace(path)
    cs = [e for e in events if e["cat"] == "__counter__"]
    assert [e["args"] for e in cs] == [3.5, 7.25, 4.0]
    assert meta["host_cores"] == (os.cpu_count() or 1)
    # counters feed the report's counter summary
    rep = an.analyze_events(events)
    assert rep["counters"]["ps.tau_p95"] == {"last": 7.25, "max": 7.25}


def test_counters_are_never_sampled_out():
    trace.enable(ring_size=4096, sample=0.01)
    try:
        for i in range(20):
            trace.counter("c", i)
        cs = [e for e in trace.events() if e["cat"] == "__counter__"]
        assert len(cs) == 20
    finally:
        trace.disable()


def test_save_gzip_and_transparent_read(tmp_path):
    trace.enable(ring_size=1024)
    try:
        with trace.span("worker.fetch", corr="w0:x1"):
            time.sleep(0.001)
        gz = trace.save(str(tmp_path / "trace.json.gz"))
    finally:
        trace.disable()
    with open(gz, "rb") as f:
        assert f.read(2) == b"\x1f\x8b"  # actually gzipped
    events, meta = an.load_trace(gz)
    assert any(e["name"] == "worker.fetch" for e in events)
    # suffix-free gz (a rotated rename) still reads — magic sniffing
    renamed = str(tmp_path / "trace.rotated")
    os.rename(gz, renamed)
    events2, _ = an.load_trace(renamed)
    assert len(events2) == len(events)


def test_save_rotation_caps_growth(tmp_path):
    path = str(tmp_path / "trace.json")
    for k in range(3):
        trace.enable(ring_size=1024)
        try:
            with trace.span("ps.fold"):
                pass
            trace.save(path, max_bytes=1, keep=2)  # always rotate
        finally:
            trace.disable()
    assert os.path.exists(path)
    assert os.path.exists(path + ".1")
    assert os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3")  # capped at keep
    an.load_trace(path + ".2")              # rotated files stay readable


def test_store_dump_gz_roundtrip(tmp_path):
    st = TimeSeriesStore()
    st.sample("ps.commits", 1.0, 5, "counter")
    path = st.dump(str(tmp_path / "series.json.gz"))
    with open(path, "rb") as f:
        assert f.read(2) == b"\x1f\x8b"
    st2 = TimeSeriesStore.load(path)
    assert st2.last("ps.commits") == 5.0


def test_cli_analyze_json_and_series(tmp_path, capsys):
    from distkeras_tpu.observability.__main__ import main

    trace.enable(ring_size=4096)
    try:
        evs, _ = serial_window(0.0, wid=0, n=1)
        for e in evs:
            trace.record(e["name"], e["t0_ns"], e["t0_ns"] + e["dur_ns"],
                         corr=e["corr"])
        path = trace.save(str(tmp_path / "t.json.gz"))
    finally:
        trace.disable()
    st = TimeSeriesStore()
    st.sample("ps.tau_p95", 1.0, 21.0)
    series = st.dump(str(tmp_path / "s.json.gz"))
    rc = main(["analyze", path, "--series", series, "--json"])
    out = capsys.readouterr().out
    rep = json.loads(out)
    assert rc == 0
    assert rep["training"]["windows"] == 1
    assert rep["counters"]["ps.tau_p95"]["last"] == 21.0
    # human-readable mode prints the verdict line
    rc2 = main(["analyze", path])
    assert rc2 == 0
    assert "regime:" in capsys.readouterr().out
    with pytest.raises(SystemExit):
        main(["analyze", str(tmp_path / "missing.json")])


# -- the watchtower bridge ----------------------------------------------------


def test_regime_tracker_and_bottleneck_shift_rule():
    from distkeras_tpu.observability.watch import (
        BottleneckShiftRule,
        Watchdog,
    )

    store = TimeSeriesStore()
    tracker = an.RegimeTracker()
    # four compute-bound slices, then the run turns fsync-bound
    t_ms = 0.0
    for tick in range(4):
        evs, _ = serial_window(t_ms, wid=0, n=tick + 1, compute_ms=50.0,
                               wait_ms=0.5, append_ms=0.2, wire_ms=0.5,
                               lock_ms=0.1, fold_ms=0.5, decode_ms=0.2)
        t_ms += 200.0
        tracker.observe(evs, store, float(tick))
    for tick in range(4, 6):
        evs, _ = serial_window(t_ms, wid=0, n=tick + 1, compute_ms=1.0,
                               wait_ms=80.0, append_ms=10.0,
                               wire_ms=0.5, lock_ms=0.1, fold_ms=0.5,
                               decode_ms=0.2)
        t_ms += 200.0
        tracker.observe(evs, store, float(tick))
    codes = [v for _, v in store.get("analyze.regime_code").points()]
    assert codes[0] == an.regime_code("compute-bound")
    assert codes[-1] == an.regime_code("fsync-bound")

    rule = BottleneckShiftRule(persistence=1)
    dog = Watchdog(store, rules=[rule])
    fired = dog.evaluate(now=10.0)
    assert [a["kind"] for a in fired] == ["bottleneck_shift"]
    assert fired[0]["detail"]["from"] == "compute-bound"
    assert fired[0]["detail"]["to"] == "fsync-bound"


def test_shift_rule_quiet_on_stable_regime():
    from distkeras_tpu.observability.watch import (
        BottleneckShiftRule,
        Watchdog,
    )

    store = TimeSeriesStore()
    for i in range(6):
        store.sample("analyze.regime_code", float(i),
                     an.regime_code("compute-bound"))
    dog = Watchdog(store, rules=[BottleneckShiftRule(persistence=1)])
    assert dog.evaluate(now=7.0) == []
    # too few points: no judgment either way
    st2 = TimeSeriesStore()
    st2.sample("analyze.regime_code", 0.0, 0.0)
    dog2 = Watchdog(st2, rules=[BottleneckShiftRule(persistence=1)])
    assert dog2.evaluate(now=1.0) == []


# -- end-to-end acceptance ----------------------------------------------------


@pytest.mark.filterwarnings("ignore")
def test_analyze_knob_end_to_end():
    """analyze=True implies tracing, runs post-hoc, lands the report in
    analysis_, and releases the recorder (a no-trace run pays nothing —
    the off-path allocation-freeness itself is pinned in
    test_observability)."""
    ds = blobs_dataset(n=256)
    t = dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", learning_rate=0.05,
                num_workers=2, batch_size=16, communication_window=2,
                num_epoch=2, backend="ps", ps_transport="inprocess",
                analyze=True)
    assert t.trace is True          # implied
    t.train(ds, shuffle=True)
    rep = t.analysis_
    assert rep is not None and rep["verdict"]["regime"] in an.REGIMES
    assert rep["training"]["windows"] == 16       # 2 workers × 8
    assert rep["degraded"] is False
    assert not trace.enabled()      # recorder released
    # a run WITHOUT the knob leaves analysis_ empty
    t2 = dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                 worker_optimizer="sgd", learning_rate=0.05,
                 num_workers=2, batch_size=16, communication_window=2,
                 num_epoch=1, backend="ps", ps_transport="inprocess")
    t2.train(ds, shuffle=True)
    assert t2.analysis_ is None
    assert not trace.enabled()


def test_analyze_knob_validation():
    with pytest.raises(ValueError, match="analyze"):
        dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", num_workers=2, batch_size=16,
                num_epoch=1, backend="collective", analyze=True)


@pytest.mark.filterwarnings("ignore")
def test_straggler_is_named_end_to_end():
    """Acceptance: a FaultPlan.straggle={wid: s} run names that worker
    as the dominant wait source — its boundary sleeps land in the stall
    attribution, not in invented phase time."""
    from distkeras_tpu.resilience.faults import FaultPlan

    ds = blobs_dataset(n=512)
    plan = FaultPlan(straggle={1: 0.2})
    t = dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", learning_rate=0.05,
                num_workers=4, batch_size=16, communication_window=2,
                num_epoch=2, backend="ps", ps_transport="inprocess",
                fault_plan=plan, analyze=True)
    with plan:
        t.train(ds, shuffle=True)
    assert plan.stats()["straggles"] > 0
    tr = t.analysis_["training"]
    assert tr["dominant_wait_worker"] == 1
    assert 1 in tr["stragglers"]
    # the sleeps are attributed as stall, dwarfing the healthy workers'
    assert tr["workers"]["1"]["stall_ms"] > \
        10 * max(tr["workers"]["0"]["stall_ms"],
                 tr["workers"]["2"]["stall_ms"], 1.0)
    # and the top recommendation names the straggler
    assert any("worker 1" in r
               for r in t.analysis_["verdict"]["recommendations"])


def _durable_exchange_run(tmp_path, window, per_record_fsync,
                          workers=8, rounds=6, compute_s=0.05):
    """Drive the REAL ParameterServer + CommitLog + flight recorder with
    the worker loop's span protocol — real folds, real WAL
    appends/waits/fsyncs — and analyze the recording. Compute is a
    sleep-simulated device (each worker owns its accelerator, so
    windows run in parallel and commits arrive together — bench's
    exchange leg simulates the device the same way, and it is what
    makes group-commit batching realistic instead of serialized by the
    suite host's single core). The trainer variant of this scenario
    drowns in per-device XLA compile time under the 8-fake-device
    conftest; this harness is the same PS/WAL/trace/analyze pipeline
    with the compile confound removed."""
    import threading

    import numpy as np

    from distkeras_tpu.parallel.merge_rules import DynSGDMerge
    from distkeras_tpu.parameter_servers import ParameterServer

    ps = ParameterServer(
        {"w": np.zeros(8192, np.float32)}, DynSGDMerge(), workers,
        wal_dir=str(tmp_path / f"wal-{window}"),
        wal_group_window=window,
    )
    if per_record_fsync:
        ps._wal.fsync_every = 1   # the PR 5 per-record durability cadence
    delta = {"w": np.full(8192, 0.01, np.float32)}
    # synchronized window boundaries: commits arrive as a burst, the
    # data-parallel shape that is the per-record log's worst case and
    # group commit's best — exactly the contrast the knob exists for
    gate = threading.Barrier(workers)
    trace.enable(ring_size=65536)
    try:
        def work(wid):
            ps.pull(wid)
            for r in range(1, rounds + 1):
                gate.wait()
                trace.set_corr(f"w{wid}:x{r}")
                t0 = time.perf_counter()
                time.sleep(compute_s)     # the simulated device window
                t1 = time.perf_counter()
                trace.record("worker.compute", int(t0 * 1e9),
                             int(t1 * 1e9))
                trace.record("worker.fetch", int(t0 * 1e9),
                             int(t1 * 1e9))
                t2 = time.perf_counter()
                ps.commit(wid, delta, seq=r)
                trace.record("worker.commit", int(t2 * 1e9),
                             int(time.perf_counter() * 1e9))

        threads = [threading.Thread(target=work, args=(w,))
                   for w in range(workers)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        events = trace.events()
        dropped = trace.live_dropped()
    finally:
        trace.disable()
        ps._wal.close()
    return an.analyze_events(events, dropped=dropped)


@pytest.mark.filterwarnings("ignore")
def test_fsync_bound_w1_vs_w8_end_to_end(tmp_path, monkeypatch):
    """Acceptance: on the same (deterministically slowed) log device, a
    per-record-fsync durable run classifies fsync-bound while the w8
    group-commit run does not — one fsync per batch amortizes the tail
    below the compute bill. The fsync sleep stands in for a slow disk
    (tmpfs CI disks would otherwise make fsync free and the leg
    meaningless)."""
    from distkeras_tpu.resilience import wal as walmod

    real_fsync = walmod.os.fsync

    def slow_fsync(fd):
        time.sleep(0.010)
        return real_fsync(fd)

    monkeypatch.setattr(walmod.os, "fsync", slow_fsync)
    rep1 = _durable_exchange_run(tmp_path, window=1,
                                 per_record_fsync=True)
    rep8 = _durable_exchange_run(tmp_path, window=8,
                                 per_record_fsync=False)
    assert rep1["verdict"]["regime"] == "fsync-bound", \
        rep1["training"]["union_ms"]
    assert rep8["verdict"]["regime"] != "fsync-bound", \
        rep8["training"]["union_ms"]
    # the structural claim behind the flip: grouping amortized the
    # durable wall (union accounting — shared waits count once)
    assert rep1["training"]["union_ms"]["wal"] > \
        1.5 * rep8["training"]["union_ms"]["wal"]
    assert any("ps_wal_group_window" in r
               for r in rep1["verdict"]["recommendations"])


@pytest.mark.filterwarnings("ignore")
def test_pipelined_overlap_end_to_end():
    """Acceptance: ps_pipeline_depth=1 reports a high hidden-exchange
    fraction, depth 0 reports ~none — the per-run measurement of PR
    10's overlap claim (bench's RTT oracle pins the wire-count half)."""
    ds = blobs_dataset(n=256)
    kw = dict(loss="sparse_softmax_cross_entropy",
              worker_optimizer="sgd", learning_rate=0.05,
              num_workers=2, batch_size=16, communication_window=2,
              num_epoch=2, backend="ps", ps_transport="socket",
              analyze=True)
    t1 = dk.DOWNPOUR(model_spec(), ps_pipeline_depth=1, **kw)
    t1.train(ds, shuffle=True)
    t0 = dk.DOWNPOUR(model_spec(), **kw)
    t0.train(ds, shuffle=True)
    f1 = t1.analysis_["training"]["overlap"]["fraction"]
    f0 = t0.analysis_["training"]["overlap"]["fraction"]
    # nominal ~0.9 alone; the tail-flush window (never hidden — there
    # is no next window to hide under) plus full-suite GIL scramble has
    # been observed to pull it to ~0.54, so the bound sits below that
    # with the serial run's ~0.0 still an order of magnitude away
    assert f1 > 0.4, t1.analysis_["training"]["overlap"]
    assert f0 < 0.1, t0.analysis_["training"]["overlap"]


@pytest.mark.filterwarnings("ignore")
def test_traced_watched_run_feeds_regime_series(tmp_path):
    """watch=True + trace=True wires the analyst's online shadow: the
    dump carries analyze.regime_code samples and the default rule set
    includes the shift rule without firing on a stable run."""
    ds = blobs_dataset(n=512)
    t = dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", learning_rate=0.05,
                num_workers=2, batch_size=16, communication_window=2,
                num_epoch=2, backend="ps", ps_transport="inprocess",
                trace=True, watch=True, scrape_interval=0.05,
                watch_dir=str(tmp_path / "watch"))
    t.train(ds, shuffle=True)
    doc = json.loads(open(t.watch_path_).read())
    assert "analyze.regime_code" in doc["series"], sorted(doc["series"])
    assert not any(a["kind"] == "bottleneck_shift"
                   for a in t.watch_alerts_["log"])
