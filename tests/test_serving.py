"""Serving tier (distkeras_tpu/serving): block-paged KV cache, continuous
batching, and the socket front end.

The load-bearing oracle: block-paged decode through the engine must emit
EXACTLY the tokens dense-cache :func:`generate` emits for the same prompt
(greedy — bf16 and f32), no matter what batch the scheduler mixed the
request into. Paged attention is an addressing change, never a different
model.
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import networking
from distkeras_tpu.models import generate, transformer_lm
from distkeras_tpu.serving import (
    BlockAllocator,
    BlockPoolExhausted,
    GenerationClient,
    GenerationEngine,
    GenerationServer,
    ResilientGenerationClient,
    per_row_new_token_counts,
)

VOCAB, MAXLEN, DIM, HEADS, DEPTH = 64, 64, 32, 4, 2


@pytest.fixture(scope="module")
def lm():
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=DEPTH, dtype=jnp.float32,
                          pos_embedding="rope", kv_heads=2)
    params, _ = spec.init_np(0)
    return spec, params


@pytest.fixture(scope="module")
def lm16():
    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=DIM, heads=HEADS,
                          depth=DEPTH, dtype=jnp.bfloat16)
    params, _ = spec.init_np(0)
    return spec, params


def _prompts(rng, lengths):
    return [rng.integers(0, VOCAB, (lp,)).astype(np.int32)
            for lp in lengths]


# -- block allocator ----------------------------------------------------------


def test_allocator_alloc_free_and_leak_accounting():
    a = BlockAllocator(num_blocks=9, block_size=4)
    assert a.capacity == 8            # block 0 is scratch
    b1 = a.alloc(3)
    b2 = a.alloc(5)
    assert a.used_blocks == 8 and a.free_blocks == 0
    assert 0 not in b1 + b2           # scratch never handed out
    with pytest.raises(BlockPoolExhausted):
        a.alloc(1)
    a.free(b1)
    assert a.used_blocks == 5 and a.high_water == 8
    with pytest.raises(ValueError, match="double-free"):
        a.free(b1)
    a.free(b2)
    assert a.used_blocks == 0
    # deterministic: fresh allocator hands out lowest ids first, and a
    # freed-then-realloc'd pool repeats the same order
    a2 = BlockAllocator(num_blocks=9, block_size=4)
    assert a2.alloc(3) == [1, 2, 3]
    assert a.alloc(3) == [1, 2, 3]


def test_per_row_new_token_counts():
    toks = np.array([[3, 5, 5, 5], [1, 2, 3, 4], [5, 0, 0, 5]])
    np.testing.assert_array_equal(
        per_row_new_token_counts(toks, eos_id=5), [2, 4, 1]
    )
    np.testing.assert_array_equal(
        per_row_new_token_counts(toks, eos_id=None), [4, 4, 4]
    )


# -- paged-cache vs dense-cache parity (the acceptance oracle) ----------------


def _engine_parity(spec, params, lengths, max_new=12, **eng_kw):
    rng = np.random.default_rng(7)
    eng = GenerationEngine(spec, params, max_batch=4, block_size=8,
                           **eng_kw)
    reqs = [(p, eng.submit(p, max_new_tokens=max_new))
            for p in _prompts(rng, lengths)]
    eng.run_until_idle()
    for p, r in reqs:
        oracle = generate(spec, params, p[None], max_new)[0, len(p):]
        np.testing.assert_array_equal(r.result(0), oracle)
    s = eng.stats()
    assert s["completed"] == len(lengths)
    assert s["blocks_in_use"] == 0, "blocks leaked across retirements"
    return s


def test_paged_decode_matches_dense_oracle_f32(lm):
    """Greedy engine output == dense generate() per request, bitwise, with
    ragged prompt lengths (block-aligned and not) mixed in one batch —
    rope+GQA exercise the per-row angle/table paths."""
    spec, params = lm
    s = _engine_parity(spec, params, [8, 13, 16, 5, 24, 9])
    # continuous batching actually batched (not serialized admissions)
    assert s["mean_batch_occupancy"] > 1.5


def test_paged_decode_matches_dense_oracle_bf16(lm16):
    """The acceptance-criteria dtype: block-paged decode bit-identical to
    the dense-cache oracle in bf16, greedy."""
    spec, params = lm16
    _engine_parity(spec, params, [8, 16, 11, 24])


def test_paged_sampling_deterministic_and_valid(lm):
    spec, params = lm
    rng = np.random.default_rng(3)
    p = rng.integers(0, VOCAB, (9,)).astype(np.int32)
    eng = GenerationEngine(spec, params, max_batch=2, block_size=8)
    r1 = eng.submit(p, max_new_tokens=10, temperature=0.8, top_k=8, seed=5)
    r2 = eng.submit(p, max_new_tokens=10, temperature=0.8, top_k=8, seed=5)
    r3 = eng.submit(p, max_new_tokens=10, temperature=0.8, top_k=8, seed=6)
    eng.run_until_idle()
    t1, t2, t3 = r1.result(0), r2.result(0), r3.result(0)
    np.testing.assert_array_equal(t1, t2)   # same seed → same stream,
    assert not np.array_equal(t1, t3)       # whatever batch row it landed in
    assert t1.min() >= 0 and t1.max() < VOCAB


def test_engine_eos_retires_early(lm):
    spec, params = lm
    # find the greedy stream, then use one of its tokens as eos
    p = np.arange(10, dtype=np.int32) % VOCAB
    oracle = generate(spec, params, p[None], 12)[0, 10:]
    eos = int(oracle[4])
    eng = GenerationEngine(spec, params, max_batch=2, block_size=8)
    r = eng.submit(p, max_new_tokens=12, eos_id=eos)
    eng.run_until_idle()
    toks = r.result(0)
    assert toks[-1] == eos and len(toks) <= 12
    np.testing.assert_array_equal(toks, oracle[:len(toks)])
    assert eng.stats()["blocks_in_use"] == 0


def test_engine_validates_requests(lm):
    spec, params = lm
    eng = GenerationEngine(spec, params, max_batch=2, block_size=8)
    with pytest.raises(ValueError, match="1-D"):
        eng.submit(np.ones((2, 3), np.int32))
    with pytest.raises(ValueError, match="maxlen"):
        eng.submit(np.ones(60, np.int32), max_new_tokens=16)
    with pytest.raises(ValueError, match="top_k"):
        eng.submit(np.ones(4, np.int32), top_k=0)
    with pytest.raises(ValueError, match="eos_id"):
        eng.submit(np.ones(4, np.int32), eos_id=VOCAB)
    with pytest.raises(ValueError, match="vocab"):
        eng.submit(np.full(4, VOCAB, np.int32))
    with pytest.raises(TypeError, match="TransformerLM"):
        GenerationEngine(object(), params)


# -- scheduler properties -----------------------------------------------------


def test_scheduler_seeded_mix_completes_without_starvation(lm):
    """Property test: a seeded mix of short/long prompts against a small
    slot+block budget — every admitted request completes, FIFO admission
    starves nobody (completion covers ALL requests), and the block pool
    is empty after the last retirement."""
    spec, params = lm
    rng = np.random.default_rng(11)
    eng = GenerationEngine(spec, params, max_batch=3, block_size=8,
                           num_blocks=3 * (MAXLEN // 8) + 1, max_queue=32)
    lengths = [int(x) for x in rng.integers(4, 40, size=14)]
    reqs = []
    for i, lp in enumerate(lengths):
        p = rng.integers(0, VOCAB, (lp,)).astype(np.int32)
        # long generations mixed with short ones
        reqs.append(eng.submit(p, max_new_tokens=4 + (i % 3) * 8))
    eng.run_until_idle()
    assert all(r.state == "done" for r in reqs), \
        [(r.id, r.state) for r in reqs]
    for r, lp in zip(reqs, lengths):
        assert len(r.new_tokens) == r.max_new_tokens
    s = eng.stats()
    assert s["completed"] == len(reqs)
    assert s["blocks_in_use"] == 0 and s["active"] == 0 and s["queued"] == 0
    assert s["blocks_high_water"] <= eng.allocator.capacity


def test_cancel_frees_blocks_midflight(lm):
    spec, params = lm
    eng = GenerationEngine(spec, params, max_batch=2, block_size=8)
    r1 = eng.submit(np.ones(8, np.int32), max_new_tokens=30)
    r2 = eng.submit(np.ones(8, np.int32), max_new_tokens=5)
    for _ in range(3):
        eng.step()
    assert eng.stats()["blocks_in_use"] > 0
    eng.cancel(r1)
    eng.run_until_idle()
    assert r1.state == "cancelled" and r2.state == "done"
    with pytest.raises(RuntimeError, match="cancelled"):
        r1.result(0)
    assert eng.stats()["blocks_in_use"] == 0


def test_speculative_engine_matches_generate_and_accepts_self_draft(lm):
    """Greedy speculative serving: exact vs the dense oracle, per-row
    advancement (no batch-min lockstep), and a self-draft accepts every
    proposal — including across fully-accepted rounds (the draft-cache
    hole one extra draft step per round exists to close)."""
    spec, params = lm
    s = _engine_parity(spec, params, [8, 11, 16], max_new=12,
                       draft=spec, draft_params=params, spec_tokens=3)
    assert s["spec_acceptance"] == 1.0
    assert s["spec_rounds"] < 12        # fewer target passes than tokens
    eng = GenerationEngine(spec, params, draft=spec, draft_params=params,
                           spec_tokens=3, max_batch=2, block_size=8)
    with pytest.raises(ValueError, match="greedy-only"):
        eng.submit(np.ones(6, np.int32), temperature=0.5)


# -- socket front end ---------------------------------------------------------


def _start_server(spec, params, **eng_kw):
    eng = GenerationEngine(spec, params, **eng_kw)
    srv = GenerationServer(eng, poll_interval=0.02)
    srv.start()
    return srv


def test_server_concurrent_clients_with_midstream_kill(lm):
    """N concurrent client threads (mixed greedy/sampled) all complete
    with greedy rows matching the dense oracle, while one client killed
    mid-stream has its request cancelled and its blocks freed — a dead
    connection cannot leak pool memory."""
    spec, params = lm
    srv = _start_server(spec, params, max_batch=4, block_size=8,
                        max_queue=32)
    results, errs = {}, []

    def client(i):
        try:
            c = GenerationClient("127.0.0.1", srv.port)
            p = np.random.default_rng(i).integers(
                0, VOCAB, (6 + i,)).astype(np.int32)
            kw = {} if i % 2 == 0 else {
                "temperature": 0.7, "top_k": 8, "seed": i}
            results[i] = (p, c.generate(p, max_new_tokens=8, **kw), kw)
            c.close()
        except Exception as e:   # surfaced below
            errs.append((i, e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    # the victim: submit a long generation, then slam the socket shut
    k = networking.connect("127.0.0.1", srv.port)
    networking.send_data(k, {"action": "generate",
                             "prompt": np.ones(8, np.int32),
                             "max_new_tokens": 40})
    time.sleep(0.1)
    k.close()
    for t in threads:
        t.join(30)
    try:
        assert not errs, errs
        assert len(results) == 6
        for i, (p, toks, kw) in results.items():
            if not kw:
                oracle = generate(spec, params, p[None], 8)[0, len(p):]
                np.testing.assert_array_equal(toks, oracle)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            s = srv.stats()
            if s["cancelled"] >= 1 and s["blocks_in_use"] == 0 \
                    and s["active"] == 0:
                break
            time.sleep(0.02)
        assert s["completed"] >= 6
        assert s["cancelled"] >= 1 and s["dead_connections"] >= 1
        assert s["blocks_in_use"] == 0, "dead client leaked blocks"
    finally:
        srv.stop()


def test_server_backpressure_and_resilient_client(lm):
    """A flooded bounded queue answers busy (typed, retryable); the
    reconnecting client rides the backpressure out and completes."""
    from distkeras_tpu.resilience import RetryPolicy

    spec, params = lm
    srv = _start_server(spec, params, max_batch=1, block_size=8,
                        max_queue=1)
    busy, done = [], []

    def flood(i):
        c = GenerationClient("127.0.0.1", srv.port)
        try:
            done.append(c.generate(np.ones(8, np.int32),
                                   max_new_tokens=16))
        except networking.ServerBusyError:
            busy.append(i)
        finally:
            c.close()

    threads = [threading.Thread(target=flood, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    try:
        assert busy, "expected at least one busy rejection"
        assert done, "expected at least one completion"
        rc = ResilientGenerationClient(
            lambda: GenerationClient("127.0.0.1", srv.port),
            policy=RetryPolicy(max_attempts=100, base_delay=0.05,
                               max_delay=0.5, deadline=60),
        )
        toks = rc.generate(np.ones(8, np.int32), max_new_tokens=4)
        assert toks.shape == (4,)
        rc.close()
    finally:
        srv.stop()


def test_server_stats_and_bad_request(lm):
    spec, params = lm
    srv = _start_server(spec, params, max_batch=2, block_size=8)
    try:
        c = GenerationClient("127.0.0.1", srv.port)
        with pytest.raises(networking.ProtocolError, match="bad_request"):
            c.generate(np.ones(80, np.int32), max_new_tokens=8)
        toks = c.generate(np.ones(6, np.int32), max_new_tokens=4)
        assert toks.shape == (4,)
        s = c.stats()
        assert s["completed"] == 1 and s["connections"] >= 1
        c.close()
    finally:
        srv.stop()


def test_serve_smoke_16_concurrent(lm16):
    """The CI serve-smoke contract: a tiny bf16 LM server under 16
    concurrent clients — every request completes with the right shape and
    the stats blob is JSON-serializable."""
    import json

    spec, params = lm16
    srv = _start_server(spec, params, max_batch=4, block_size=8,
                        max_queue=32)
    results, errs = {}, []

    def client(i):
        try:
            c = GenerationClient("127.0.0.1", srv.port)
            p = np.random.default_rng(i).integers(
                0, VOCAB, (4 + i % 7,)).astype(np.int32)
            results[i] = c.generate(p, max_new_tokens=6, seed=i)
            c.close()
        except Exception as e:
            errs.append((i, e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    try:
        assert not errs, errs
        assert len(results) == 16
        assert all(v.shape == (6,) for v in results.values())
        blob = json.dumps(srv.stats())
        parsed = json.loads(blob)
        assert parsed["completed"] >= 16 and parsed["blocks_in_use"] == 0
    finally:
        srv.stop()


def test_graceful_drain_completes_inflight(lm):
    spec, params = lm
    eng = GenerationEngine(spec, params, max_batch=2, block_size=8)
    srv = GenerationServer(eng, poll_interval=0.02)
    srv.start()
    c = GenerationClient("127.0.0.1", srv.port)
    out = {}

    def go():
        try:
            out["toks"] = c.generate(np.ones(8, np.int32),
                                     max_new_tokens=20)
        except Exception as e:  # surfaced below, not a bare KeyError
            out["err"] = e

    t = threading.Thread(target=go)
    t.start()
    # wait until it is actually running, then drain
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and eng.stats()["active"] == 0:
        time.sleep(0.01)
    srv.stop(drain=True)
    # generous join + surfaced client error: under full-suite load the
    # in-flight request's decode (plus any jit compile it triggers) has
    # been seen to outlast 10 s — a silent join timeout or a swallowed
    # client exception then reads as a bogus KeyError on out["toks"]
    # (ISSUE 14 jitter-hardening pass)
    t.join(60)
    assert not t.is_alive(), "drained request never completed"
    assert "err" not in out, out.get("err")
    assert out["toks"].shape == (20,)
    with pytest.raises(networking.ServerBusyError):
        eng.submit(np.ones(4, np.int32))
    c.close()
