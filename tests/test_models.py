"""Model-zoo and dataset-loader coverage for the BASELINE configs.

Mesh-training smoke tests for ``lenet``/``vgg_small``/``lstm_classifier``
(configs 2/3/5) and loader tests for ``cifar10``/``imdb`` — shapes, dtypes,
mask semantics, and train/test distribution sharing, mirroring the existing
mnist/higgs loader tests in test_parity_surface.py.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu import ADAG, DOWNPOUR, DynSGD
from distkeras_tpu.datasets import cifar10, imdb, mnist
from distkeras_tpu.models import lenet, lstm_classifier, vgg_small


def losses_of(t):
    return [float(l) for l in t.get_history().losses()]


def downscale(ds, factor=2):
    """Halve image resolution — same model code, 4× less single-core CPU work."""
    from distkeras_tpu.data import Dataset

    return Dataset({
        "features": ds["features"][:, ::factor, ::factor, :],
        "label": ds["label"],
    })


@pytest.mark.slow  # conv-trainer integration; MLP/LSTM mesh trainings pin the engine in the fast tier
def test_lenet_trains_on_mesh():
    train, _ = mnist(n_train=512, n_test=16)
    t = ADAG(lenet(input_shape=(14, 14, 1), dtype=jnp.float32),
             loss="sparse_softmax_cross_entropy",
             worker_optimizer="adam", learning_rate=2e-3, num_workers=8,
             batch_size=4, communication_window=2, num_epoch=2)
    t.train(downscale(train), shuffle=True)
    ls = losses_of(t)
    assert np.all(np.isfinite(ls))
    # deterministic run (seeded shuffle): 16 windows reach ~2.0 from ~2.5
    assert np.mean(ls[-3:]) < 0.85 * ls[0], ls


@pytest.mark.slow
def test_vgg_small_trains_on_mesh():
    train, _ = cifar10(n_train=128, n_test=16)
    t = DOWNPOUR(vgg_small(input_shape=(16, 16, 3), dtype=jnp.float32),
                 loss="sparse_softmax_cross_entropy",
                 worker_optimizer="adam", learning_rate=1e-3, num_workers=8,
                 batch_size=2, communication_window=2, num_epoch=1)
    t.train(downscale(train), shuffle=True)
    ls = losses_of(t)
    assert np.all(np.isfinite(ls))
    assert np.mean(ls[-2:]) < ls[0], ls


def test_lstm_classifier_trains_on_mesh():
    train, _ = imdb(n_train=512, n_test=32, vocab=500, maxlen=32)
    model = lstm_classifier(vocab=500, maxlen=32, embed_dim=16, hidden_dim=16,
                            dtype=jnp.float32)
    t = DynSGD(model, loss="sparse_softmax_cross_entropy",
               worker_optimizer="adam", learning_rate=2e-3, num_workers=8,
               batch_size=8, communication_window=2, num_epoch=3,
               features_col=["features", "mask"])
    t.train(train, shuffle=True)
    ls = losses_of(t)
    assert np.all(np.isfinite(ls))
    assert np.mean(ls[-3:]) < ls[0], ls


def test_transformer_classifier_trains_on_mesh():
    from distkeras_tpu.models import transformer_classifier

    train, _ = imdb(n_train=512, n_test=32, vocab=500, maxlen=32)
    model = transformer_classifier(vocab=500, maxlen=32, dim=32, heads=2,
                                   depth=1, dtype=jnp.float32)
    t = ADAG(model, loss="sparse_softmax_cross_entropy",
             worker_optimizer="adam", learning_rate=1e-3, num_workers=8,
             batch_size=8, communication_window=2, num_epoch=2,
             features_col=["features", "mask"])
    t.train(train, shuffle=True)
    ls = losses_of(t)
    assert np.all(np.isfinite(ls))
    assert np.mean(ls[-3:]) < ls[0], ls


def test_cifar10_loader_shapes_and_split_distribution():
    train, test = cifar10(n_train=2000, n_test=500)
    assert train["features"].shape == (2000, 32, 32, 3)
    assert train["features"].dtype == np.float32
    assert train["label"].dtype == np.int32
    assert test["features"].shape == (500, 32, 32, 3)
    assert 0.0 <= train["features"].min() and train["features"].max() <= 1.0
    assert set(np.unique(train["label"])) <= set(range(10))
    # train/test share class templates: per-class means must correlate
    for c in range(3):
        tr_mean = train["features"][train["label"] == c].mean(axis=0).ravel()
        te_mean = test["features"][test["label"] == c].mean(axis=0).ravel()
        r = np.corrcoef(tr_mean, te_mean)[0, 1]
        assert r > 0.5, f"class {c} split correlation {r}"


def test_imdb_loader_mask_semantics():
    train, test = imdb(n_train=300, n_test=100, vocab=1000, maxlen=64)
    tok, mask, lab = train["features"], train["mask"], train["label"]
    assert tok.shape == (300, 64) and tok.dtype == np.int32
    assert mask.shape == (300, 64) and mask.dtype == np.float32
    assert set(np.unique(lab)) <= {0, 1}
    # mask is a prefix of ones followed by zeros; tokens are zero-padded
    for i in range(20):
        m = mask[i]
        length = int(m.sum())
        assert np.array_equal(m, np.r_[np.ones(length), np.zeros(64 - length)])
        assert np.all(tok[i, length:] == 0)
        assert np.all(tok[i, :length] > 0)  # real tokens, 0 reserved for pad
    # variable lengths actually occur
    assert len({int(m.sum()) for m in mask[:50]}) > 5
    # both classes present in both splits
    assert set(np.unique(test["label"])) == {0, 1}


def test_transformer_remat_matches_plain():
    """remat=True must change memory scheduling only: identical params tree,
    identical logits, identical gradients (jax.checkpoint recomputes the
    same math in the backward pass)."""
    import jax
    import optax

    from distkeras_tpu.models import transformer_classifier
    from distkeras_tpu.ops.losses import sparse_softmax_cross_entropy

    rng = np.random.default_rng(3)
    toks = rng.integers(0, 64, size=(4, 16)).astype(np.int32)
    mask = np.ones((4, 16), np.float32)
    mask[:, 12:] = 0.0
    y = rng.integers(0, 4, size=(4,)).astype(np.int32)

    # depth 1: remat wraps each block identically, so one block pins the
    # equality at half the trace/compile cost of the old depth-2 config
    kw = dict(vocab=64, maxlen=16, dim=32, heads=4, depth=1, num_classes=4,
              dtype=jnp.float32)
    plain = transformer_classifier(**kw)
    remat = transformer_classifier(**kw, remat=True)
    params, nt = plain.init_np(0)
    params_r, _ = remat.init_np(0)
    assert jax.tree.structure(params) == jax.tree.structure(params_r)

    def loss(spec, p, training):
        out, _ = spec.apply(p, nt, (toks, mask), training=training)
        return sparse_softmax_cross_entropy(y, out)

    for training in (False, True):
        ref, ref_g = jax.value_and_grad(
            lambda p: loss(plain, p, training))(params)
        got, got_g = jax.jit(jax.value_and_grad(
            lambda p: loss(remat, p, training)))(params)
        np.testing.assert_allclose(float(got), float(ref),
                                   rtol=1e-6, atol=1e-7)
        for r, g in zip(jax.tree.leaves(ref_g), jax.tree.leaves(got_g)):
            np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_resnet_batchnorm_trains_on_mesh():
    """BatchNorm running stats must flow through the stacked nt path: they
    start at (0 mean, 1 var), move during training, and the returned
    worker-0 stats drive eval-mode inference."""
    from distkeras_tpu.models import resnet_small

    train, _ = cifar10(n_train=256, n_test=32)
    model = resnet_small(widths=(8, 16), blocks_per_stage=1,
                         dtype=jnp.float32)
    t = DOWNPOUR(model, loss="sparse_softmax_cross_entropy",
                 worker_optimizer="adam", learning_rate=3e-3, num_workers=8,
                 batch_size=8, communication_window=2, num_epoch=1)
    params = t.train(train, shuffle=True)
    ls = losses_of(t)
    assert np.all(np.isfinite(ls))
    # (no loss-decrease assert: 2 windows of adam are noise; learning for
    # this family is pinned by test_fsdp/test_sync_batchnorm — this test's
    # property is the BatchNorm nt path)
    # stats moved off their init (mean 0 / var 1)
    bs = t.trained_nt_["batch_stats"]
    mean0 = np.asarray(bs["bn_stem"]["mean"])
    var0 = np.asarray(bs["bn_stem"]["var"])
    assert np.any(np.abs(mean0) > 1e-4)
    assert np.any(np.abs(var0 - 1.0) > 1e-4)
    # eval-mode inference with the trained stats
    x = train["features"][:16]
    out, _ = model.apply(params, t.trained_nt_, x, False)
    assert out.shape == (16, 10)
    assert np.all(np.isfinite(np.asarray(out)))


def test_sync_batchnorm_equals_global_batch():
    """sync_bn=True: W stacked workers normalizing with pmean over the
    worker axis produce exactly the statistics of the concatenated global
    batch — and the engine's window step accepts the model."""
    import jax

    from distkeras_tpu.models import resnet_small
    from distkeras_tpu.parallel.local_sgd import WORKER_AXIS

    rng = np.random.default_rng(0)
    W, B = 4, 8
    x = rng.normal(size=(W, B, 8, 8, 3)).astype(np.float32)

    spec = resnet_small(widths=(8,), blocks_per_stage=1, dtype=jnp.float32,
                        sync_bn=True)
    params, nt = spec.init_np(0)

    # vmapped-with-axis-name (the engine's layout) vs one flat batch
    out_w, nt_w = jax.vmap(
        lambda xs: spec.apply(params, nt, xs, True),
        axis_name=WORKER_AXIS,
    )(x)
    flat_spec = resnet_small(widths=(8,), blocks_per_stage=1,
                             dtype=jnp.float32)
    out_flat, nt_flat = flat_spec.apply(params, nt,
                                        x.reshape(W * B, 8, 8, 3), True)
    np.testing.assert_allclose(
        np.asarray(out_w).reshape(W * B, -1), np.asarray(out_flat),
        rtol=2e-4, atol=2e-5,
    )
    # every worker carries identical (global) running stats
    means = np.asarray(nt_w["batch_stats"]["bn_stem"]["mean"])
    assert np.allclose(means, means[0:1], atol=1e-6)
    np.testing.assert_allclose(
        means[0], np.asarray(nt_flat["batch_stats"]["bn_stem"]["mean"]),
        rtol=1e-5, atol=1e-6,
    )

    # end-to-end through a trainer window on the mesh (16×16 crop keeps the
    # compile small; the property is 'the engine accepts a worker-axis
    # collective model', not image scale)
    from distkeras_tpu import ADAG
    from distkeras_tpu.data import Dataset
    from distkeras_tpu.datasets import cifar10

    train, _ = cifar10(n_train=128, n_test=16)
    small = Dataset({"features": train["features"][:, ::2, ::2, :],
                     "label": train["label"]})
    t = ADAG(resnet_small(widths=(8,), dtype=jnp.float32, sync_bn=True),
             loss="sparse_softmax_cross_entropy", worker_optimizer="adam",
             learning_rate=1e-3, num_workers=8, batch_size=8,
             communication_window=1, num_epoch=1)
    t.train(small, shuffle=True)
    assert np.all(np.isfinite([r["loss"] for r in t.get_history()
                               if "loss" in r]))


def test_sync_bn_rejected_on_ps_backend():
    """sync_bn models need the collective backend's worker axis; the PS
    backend must refuse them with a clear error, not a JAX trace error."""
    import pytest

    from distkeras_tpu import DOWNPOUR
    from distkeras_tpu.datasets import cifar10
    from distkeras_tpu.models import resnet_small

    train, _ = cifar10(n_train=64, n_test=16)
    t = DOWNPOUR(resnet_small(widths=(8,), sync_bn=True),
                 loss="sparse_softmax_cross_entropy",
                 worker_optimizer="sgd", learning_rate=0.01, num_workers=2,
                 batch_size=8, communication_window=2, num_epoch=1,
                 backend="ps")
    with pytest.raises(ValueError, match="stacked-worker axis"):
        t.train(train)


@pytest.mark.slow  # model-level window equality; kernel-level windowed pins stay fast
def test_transformer_windowed_flash_equals_reference():
    """Model-level sliding window: the classifier with attn_impl='flash'
    (Pallas, interpret here) and attn_impl='reference' agree on logits and
    parameter gradients when both use the same attn_window."""
    import jax

    from distkeras_tpu.models import transformer_classifier
    from distkeras_tpu.ops.losses import sparse_softmax_cross_entropy

    rng = np.random.default_rng(0)
    kw = dict(vocab=128, maxlen=256, dim=32, heads=2, depth=1,
              num_classes=2, dtype=jnp.float32, attn_window=48)
    ref_spec = transformer_classifier(attn_impl="reference", **kw)
    fl_spec = transformer_classifier(attn_impl="flash", **kw)
    params, nt = ref_spec.init_np(0)
    toks = rng.integers(0, 128, size=(2, 256)).astype(np.int32)
    mask = np.ones((2, 256), np.float32)
    mask[:, 200:] = 0.0
    y = np.array([0, 1], np.int32)

    def loss(spec):
        def f(p):
            out, _ = spec.apply(p, nt, (toks, mask), training=True)
            return sparse_softmax_cross_entropy(y, out)
        return f

    with jax.default_matmul_precision("highest"):
        lr, gr = jax.value_and_grad(loss(ref_spec))(params)
        lf, gf = jax.value_and_grad(loss(fl_spec))(params)
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-4)
    flat_r = jax.tree.leaves(gr)
    flat_f = jax.tree.leaves(gf)
    for a, b in zip(flat_f, flat_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4)
