"""Model-zoo and dataset-loader coverage for the BASELINE configs.

Mesh-training smoke tests for ``lenet``/``vgg_small``/``lstm_classifier``
(configs 2/3/5) and loader tests for ``cifar10``/``imdb`` — shapes, dtypes,
mask semantics, and train/test distribution sharing, mirroring the existing
mnist/higgs loader tests in test_parity_surface.py.
"""

import jax.numpy as jnp
import numpy as np

from distkeras_tpu import ADAG, DOWNPOUR, DynSGD
from distkeras_tpu.datasets import cifar10, imdb, mnist
from distkeras_tpu.models import lenet, lstm_classifier, vgg_small


def losses_of(t):
    return [float(l) for l in t.get_history().losses()]


def downscale(ds, factor=2):
    """Halve image resolution — same model code, 4× less single-core CPU work."""
    from distkeras_tpu.data import Dataset

    return Dataset({
        "features": ds["features"][:, ::factor, ::factor, :],
        "label": ds["label"],
    })


def test_lenet_trains_on_mesh():
    train, _ = mnist(n_train=512, n_test=16)
    t = ADAG(lenet(input_shape=(14, 14, 1), dtype=jnp.float32),
             loss="sparse_softmax_cross_entropy",
             worker_optimizer="adam", learning_rate=2e-3, num_workers=8,
             batch_size=4, communication_window=2, num_epoch=4)
    t.train(downscale(train), shuffle=True)
    ls = losses_of(t)
    assert np.all(np.isfinite(ls))
    assert np.mean(ls[-3:]) < ls[0] / 2, ls


def test_vgg_small_trains_on_mesh():
    train, _ = cifar10(n_train=128, n_test=16)
    t = DOWNPOUR(vgg_small(input_shape=(16, 16, 3), dtype=jnp.float32),
                 loss="sparse_softmax_cross_entropy",
                 worker_optimizer="adam", learning_rate=5e-4, num_workers=8,
                 batch_size=2, communication_window=2, num_epoch=3)
    t.train(downscale(train), shuffle=True)
    ls = losses_of(t)
    assert np.all(np.isfinite(ls))
    assert np.mean(ls[-2:]) < ls[0], ls


def test_lstm_classifier_trains_on_mesh():
    train, _ = imdb(n_train=512, n_test=32, vocab=500, maxlen=32)
    model = lstm_classifier(vocab=500, maxlen=32, embed_dim=16, hidden_dim=16,
                            dtype=jnp.float32)
    t = DynSGD(model, loss="sparse_softmax_cross_entropy",
               worker_optimizer="adam", learning_rate=2e-3, num_workers=8,
               batch_size=8, communication_window=2, num_epoch=3,
               features_col=["features", "mask"])
    t.train(train, shuffle=True)
    ls = losses_of(t)
    assert np.all(np.isfinite(ls))
    assert np.mean(ls[-3:]) < ls[0], ls


def test_transformer_classifier_trains_on_mesh():
    from distkeras_tpu.models import transformer_classifier

    train, _ = imdb(n_train=512, n_test=32, vocab=500, maxlen=32)
    model = transformer_classifier(vocab=500, maxlen=32, dim=32, heads=2,
                                   depth=1, dtype=jnp.float32)
    t = ADAG(model, loss="sparse_softmax_cross_entropy",
             worker_optimizer="adam", learning_rate=1e-3, num_workers=8,
             batch_size=8, communication_window=2, num_epoch=2,
             features_col=["features", "mask"])
    t.train(train, shuffle=True)
    ls = losses_of(t)
    assert np.all(np.isfinite(ls))
    assert np.mean(ls[-3:]) < ls[0], ls


def test_cifar10_loader_shapes_and_split_distribution():
    train, test = cifar10(n_train=2000, n_test=500)
    assert train["features"].shape == (2000, 32, 32, 3)
    assert train["features"].dtype == np.float32
    assert train["label"].dtype == np.int32
    assert test["features"].shape == (500, 32, 32, 3)
    assert 0.0 <= train["features"].min() and train["features"].max() <= 1.0
    assert set(np.unique(train["label"])) <= set(range(10))
    # train/test share class templates: per-class means must correlate
    for c in range(3):
        tr_mean = train["features"][train["label"] == c].mean(axis=0).ravel()
        te_mean = test["features"][test["label"] == c].mean(axis=0).ravel()
        r = np.corrcoef(tr_mean, te_mean)[0, 1]
        assert r > 0.5, f"class {c} split correlation {r}"


def test_imdb_loader_mask_semantics():
    train, test = imdb(n_train=300, n_test=100, vocab=1000, maxlen=64)
    tok, mask, lab = train["features"], train["mask"], train["label"]
    assert tok.shape == (300, 64) and tok.dtype == np.int32
    assert mask.shape == (300, 64) and mask.dtype == np.float32
    assert set(np.unique(lab)) <= {0, 1}
    # mask is a prefix of ones followed by zeros; tokens are zero-padded
    for i in range(20):
        m = mask[i]
        length = int(m.sum())
        assert np.array_equal(m, np.r_[np.ones(length), np.zeros(64 - length)])
        assert np.all(tok[i, length:] == 0)
        assert np.all(tok[i, :length] > 0)  # real tokens, 0 reserved for pad
    # variable lengths actually occur
    assert len({int(m.sum()) for m in mask[:50]}) > 5
    # both classes present in both splits
    assert set(np.unique(test["label"])) == {0, 1}
