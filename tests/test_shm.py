"""Shared-memory ring transport + batched local EXCHANGE (ISSUE 12).

The oracles, mirroring every transport before it:

- framing round-trips on both lanes, incl. ring-wrapping records and the
  >1-ring-capacity oversize spill path;
- shm-transport trainer runs bit-identical to the in-process transport
  (ADAG/DOWNPOUR/DynSGD, int8 pulls+commits, fused and pipelined legs,
  2-shard fan-out);
- chaos exactly-once under FaultPlan drops over the rings;
- WAL replay parity from shm-logged wire frames (verbatim
  REC_COMMIT_WIRE through the one shared decode pipeline);
- batched folds bit-identical to the same arrival order folded serially,
  and the deterministic K-folds-one-acquisition drain;
- peer death mid-ring-write surfaces as a retryable PeerDeadError and
  never wedges the server; segments unlink on close/stop/eviction —
  /dev/shm never leaks (checked by name).
"""

import os
import struct
import threading
import time
from multiprocessing import shared_memory

import numpy as np
import pytest

from distkeras_tpu import networking, shm
from distkeras_tpu.networking import PeerDeadError
from distkeras_tpu.parallel.merge_rules import DownpourMerge, DynSGDMerge
from distkeras_tpu.parameter_servers import ParameterServer
from distkeras_tpu.shm import ShmParameterServer, ShmPSClient
from tests.test_exchange import _run, _tree_equal

_PAIR_SEQ = iter(range(10_000))


def _dkshm_entries():
    try:
        return [f for f in os.listdir("/dev/shm") if f.startswith("dkshm")]
    except FileNotFoundError:  # no tmpfs: SharedMemory still works
        return []


def _conn_pair(ring_bytes=1 << 14):
    """A raw client/server endpoint pair over one fresh segment (no
    handler thread — the test drives both ends)."""
    seg = shared_memory.SharedMemory(
        create=True, name=f"dkshm_test_{os.getpid()}_{next(_PAIR_SEQ)}",
        size=shm._HDR_BYTES + 2 * ring_bytes,
    )
    struct.pack_into("<Q", seg.buf, shm._OFF_MAGIC, shm._MAGIC)
    struct.pack_into("<Q", seg.buf, shm._OFF_CAP, ring_bytes)
    waker = shm._waker_for(seg.name)
    cli = shm._ShmConn(seg, "client", waker)
    srv = shm._ShmConn(seg, "server", waker)
    return seg, cli, srv


def _drop_pair(seg, cli, srv):
    cli.close()
    srv.close()
    shm._waker_drop(seg.name)
    try:
        seg.close()
    except BufferError:
        pass
    seg.unlink()


# -- framing -----------------------------------------------------------------


def test_pickle_lane_roundtrip_wraps_the_ring():
    """Many frames through a tiny ring: records cross the wrap point
    repeatedly and every frame survives byte-exact."""
    seg, cli, srv = _conn_pair(ring_bytes=1 << 12)
    try:
        for i in range(64):  # 64 * ~200B >> 4 KiB ring: plenty of wraps
            msg = {"action": "ping", "i": i, "blob": b"x" * (i * 7 % 97)}
            cli.send_msg(msg)
            got, raw, release = srv.recv_msg()
            assert release is None and raw is not None
            assert got == msg
            srv.send_msg({"ok": True, "i": i})
            assert cli.recv_msg()[0] == {"ok": True, "i": i}
    finally:
        _drop_pair(seg, cli, srv)


def test_bulk_lane_roundtrip_views_then_release():
    """The zero-copy lane: ndarray leaves arrive as views over the
    mapped ring (no copy until the consumer says so), scalars and codec
    marks ride the skeleton, release frees the region for the next
    frame."""
    seg, cli, srv = _conn_pair(ring_bytes=1 << 14)
    try:
        rng = np.random.default_rng(0)
        for _ in range(8):  # repeated: the region must actually free
            msg = {
                "action": "commit", "worker_id": 3, "seq": 7,
                "payload": {
                    "w": rng.normal(size=(31,)).astype(np.float32),
                    "q": {"b": np.arange(5, dtype=np.int8), "s": 0.25},
                },
            }
            cli.send_msg(msg, bulk=True)
            got, raw, release = srv.recv_msg()
            assert raw is None and release is not None
            assert got["worker_id"] == 3 and got["seq"] == 7
            assert got["payload"]["q"]["s"] == 0.25
            assert np.array_equal(got["payload"]["w"], msg["payload"]["w"])
            assert np.array_equal(got["payload"]["q"]["b"],
                                  msg["payload"]["q"]["b"])
            release()
    finally:
        _drop_pair(seg, cli, srv)


def test_oversize_payload_spills_through_a_small_ring():
    """A payload several times the ring capacity streams through the
    spill path (progressive publication both sides) byte-exact — the
    >1-ring-capacity contract."""
    seg, cli, srv = _conn_pair(ring_bytes=1 << 12)  # 4 KiB rings
    try:
        big = np.arange(50_000, dtype=np.float32)  # 200 KB >> ring
        out = {}

        def reader():
            out["msg"], _, rel = srv.recv_msg(copy=True)
            assert rel is None

        t = threading.Thread(target=reader)
        t.start()
        cli.send_msg({"payload": {"w": big}}, bulk=True)  # falls back
        t.join(timeout=30)
        assert not t.is_alive()
        assert np.array_equal(out["msg"]["payload"]["w"], big)
    finally:
        _drop_pair(seg, cli, srv)


def test_duck_socket_carries_networking_frames_and_fault_hook():
    """networking.send_data/recv_data run UNCHANGED over the conn (the
    inherited client actions' path), and the _fault_hook chaos seam
    fires on both ops."""
    seg, cli, srv = _conn_pair()
    calls = []
    old = networking._fault_hook
    networking._fault_hook = lambda op, sock: calls.append(op)
    try:
        msg = {"action": "heartbeat", "worker_id": 1,
               "w": np.ones(16, np.float32)}
        networking.send_data(cli, msg)
        got, raw = networking.recv_data_raw(srv)
        assert got["action"] == "heartbeat"
        assert np.array_equal(got["w"], msg["w"])
        assert raw  # the verbatim frame bytes the WAL would log
        assert calls == ["send", "recv"]
    finally:
        networking._fault_hook = old
        _drop_pair(seg, cli, srv)


# -- peer death & leak hygiene ----------------------------------------------


def test_peer_death_mid_record_raises_retryable_and_never_wedges():
    """A writer that dies after publishing a record word but before the
    payload: the blocked reader surfaces a typed, RETRYABLE
    PeerDeadError (the satellite's liveness contract) instead of
    wedging."""
    seg, cli, srv = _conn_pair()
    try:
        # half a record: a word claiming 100 payload bytes, then death
        cli._skip_to_word_boundary_tx()
        cli._stream_tx([shm._WORD.pack((shm.FLAG_PKL << 56) | 100)])
        errs = []

        def reader():
            try:
                srv.recv_msg()
            except BaseException as e:
                errs.append(e)

        t = threading.Thread(target=reader)
        t.start()
        time.sleep(0.05)
        cli.close()  # mid-record death
        t.join(timeout=10)
        assert not t.is_alive()
        assert errs and isinstance(errs[0], PeerDeadError)
        assert errs[0].retryable
        assert isinstance(errs[0], ConnectionError)  # existing triage
    finally:
        _drop_pair(seg, cli, srv)


def test_server_unlinks_segments_on_close_stop_and_no_leaks():
    """Segments vanish from /dev/shm on client close AND on server stop
    with clients abandoned un-closed — the no-leak contract, checked by
    name."""
    before = set(_dkshm_entries())
    center = {"w": np.zeros(64, np.float32)}
    ps = ShmParameterServer(center, DownpourMerge(), 2, ring_bytes=1 << 14)
    ps.initialize()
    ps.start()
    c0 = ShmPSClient(ps, 0)
    c1 = ShmPSClient(ps, 1)  # never closed: stop() must reclaim it
    c0.pull()
    c1.pull()
    assert len(set(_dkshm_entries()) - before) == 2
    c0.close()
    deadline = time.monotonic() + 5
    while len(set(_dkshm_entries()) - before) > 1 \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(set(_dkshm_entries()) - before) == 1  # c0's reclaimed
    ps.stop()
    assert set(_dkshm_entries()) <= before  # c1's reclaimed by stop


def test_heartbeat_eviction_reclaims_abandoned_worker_segment():
    """The PR 4 lease eviction garbage-collects the shm lane too: an
    abandoned worker's lease lapses, _on_evict closes its connection,
    the handler exits, the segment unlinks."""
    before = set(_dkshm_entries())
    center = {"w": np.zeros(64, np.float32)}
    ps = ShmParameterServer(center, DownpourMerge(), 1,
                            ring_bytes=1 << 14, lease_timeout=0.2)
    ps.initialize()
    ps.start()
    try:
        c = ShmPSClient(ps, 0)
        c.heartbeat()  # registers the lease
        assert len(set(_dkshm_entries()) - before) == 1
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            time.sleep(0.1)
            if ps.stats()["evicted_workers"] >= 1 \
                    and not (set(_dkshm_entries()) - before):
                break
        assert ps.stats()["evicted_workers"] >= 1
        assert not (set(_dkshm_entries()) - before)
        # the abandoned client's next op sees typed peer death
        with pytest.raises(ConnectionError):
            c.pull()
    finally:
        ps.stop()


# -- trainer bit-identity ----------------------------------------------------


@pytest.mark.parametrize("cls_name", ["ADAG", "DOWNPOUR", "DynSGD"])
def test_trainer_shm_bit_identical_to_inprocess(cls_name):
    """The acceptance oracle: shm-transport training produces a final
    center bit-identical to the in-process transport, per merge rule."""
    _, w_inp = _run(cls_name)
    _, w_shm = _run(cls_name, ps_transport="shm")
    assert _tree_equal(w_inp, w_shm)


def test_trainer_shm_bit_identical_int8_and_fused_legs():
    """int8 commits + int8 pulls over the rings (bulk-lane codec blobs)
    match the in-process oracle bitwise, fused and unfused."""
    _, w_inp = _run("DOWNPOUR", compression="int8",
                    pull_compression="int8")
    _, w_shm = _run("DOWNPOUR", compression="int8",
                    pull_compression="int8", ps_transport="shm")
    _, w_unf = _run("DOWNPOUR", compression="int8",
                    pull_compression="int8", ps_transport="shm",
                    ps_fused_exchange=False)
    assert _tree_equal(w_inp, w_shm)
    assert _tree_equal(w_inp, w_unf)


def test_trainer_shm_pipelined_single_worker_telescopes():
    """The PR 10 pipelined telescope holds over the rings: a single
    DOWNPOUR worker's depth-1 run is bit-identical to its serial run."""
    _, w0 = _run("DOWNPOUR", ps_transport="shm")
    _, w1 = _run("DOWNPOUR", ps_transport="shm", ps_pipeline_depth=1)
    assert _tree_equal(w0, w1)


def test_trainer_shm_two_shard_fanout_bit_identical():
    """ps_num_shards=2 over the shm lane: the fan-out client opens one
    ring pair per (worker, shard) and the folds pin bit-identical to
    the single in-process center."""
    _, w1 = _run("DynSGD")
    t, w2 = _run("DynSGD", ps_num_shards=2, ps_transport="shm")
    assert _tree_equal(w1, w2)
    assert t.ps_stats_["num_shards"] == 2


# -- chaos / resilience ------------------------------------------------------


def test_shm_chaos_exactly_once_under_drops():
    """FaultPlan drops over the rings + ResilientPSClient reconnect
    (each reconnect mints a FRESH ring pair): lifetime folds == logical
    exchanges confirmed — the dedup exactly-once oracle."""
    from distkeras_tpu.resilience.faults import FaultPlan
    from distkeras_tpu.resilience.retry import (
        ResilientPSClient,
        RetryPolicy,
    )

    W, N = 2, 15
    center = {"w": np.zeros(128, np.float32)}
    delta = {"w": np.full(128, 1e-3, np.float32)}
    before = set(_dkshm_entries())
    ps = ShmParameterServer(center, DownpourMerge(), W, ring_bytes=1 << 15)
    ps.initialize()
    ps.start()
    policy = RetryPolicy(max_attempts=50, base_delay=0.005,
                         max_delay=0.05, deadline=60.0)
    clients = [
        ResilientPSClient(lambda i=i: ShmPSClient(ps, i), i, policy=policy)
        for i in range(W)
    ]
    plan = FaultPlan(seed=11, drop_recv=0.12, max_faults=60)
    errors = []

    def worker(i):
        try:
            c = clients[i]
            c.pull()
            for _ in range(N):
                out = c.exchange(i, delta)
                assert np.all(np.isfinite(out["w"]))
        except BaseException as e:
            errors.append(e)

    try:
        with plan:
            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(W)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert not errors, errors
        assert plan.stats()["drops"] > 0  # the chaos actually bit
        logical = sum(c.seq for c in clients)
        assert logical == W * N
        assert ps.num_updates == logical  # exactly-once folds
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        ps.stop()
    assert set(_dkshm_entries()) <= before  # chaos leaked nothing


def test_shm_wal_replay_parity_from_wire_frames(tmp_path):
    """A durable shm server's clients take the pickle lane (handshake
    wal_frames), so commits are logged VERBATIM (REC_COMMIT_WIRE) and
    recovery replays them through the one shared decode pipeline to a
    bit-identical server — incl. DynSGD staleness state."""
    rng = np.random.default_rng(9)
    center = {"w": rng.normal(size=(32,)).astype(np.float32)}
    deltas = [{"w": rng.normal(size=(32,)).astype(np.float32) * 0.1}
              for _ in range(3)]
    ps = ShmParameterServer(center, DynSGDMerge(), 1,
                            wal_dir=str(tmp_path / "wal"),
                            wal_group_window=1)
    ps.initialize()
    ps.start()
    try:
        c = ShmPSClient(ps, 0)
        assert c._wal_frames  # the handshake picked the verbatim lane
        c.pull()
        for i, d in enumerate(deltas):
            c.exchange(0, d, seq=i + 1, lag=True)
        live_center = ps.get_model()
        live_cur = dict(ps._pull_versions)
        live_prev = dict(ps._prev_pull_versions)
        c.close()
    finally:
        ps.stop()
    rec = ParameterServer(center, DynSGDMerge(), num_workers=1,
                          wal_dir=str(tmp_path / "wal"))
    assert rec.recovered_ and rec.num_updates == 3
    assert _tree_equal(rec.get_model(), live_center)
    assert rec._pull_versions == live_cur
    assert rec._prev_pull_versions == live_prev


# -- batched local exchange --------------------------------------------------


class _RecordingDownpour(DownpourMerge):
    """DownpourMerge that records fold arrival order via a tag leaf."""

    def __init__(self):
        super().__init__()
        self.order = []

    def fold(self, center, payload, num_workers, staleness):
        self.order.append(int(np.asarray(payload["tag"])[0]))
        return super().fold(center, payload, num_workers, staleness)


def test_batched_folds_bitwise_equal_same_order_serial():
    """The bit-identity oracle: K workers' deltas folded through the
    batched drain produce a center bitwise EQUAL to folding the same
    deltas serially in the recorded arrival order."""
    rng = np.random.default_rng(4)
    K, N = 4, 12
    center = {"tag": np.zeros(1, np.float32),
              "w": rng.normal(size=(257,)).astype(np.float32)}
    deltas = [
        {"tag": np.full(1, i, np.float32),
         "w": rng.normal(size=(257,)).astype(np.float32) * 0.1}
        for i in range(K)
    ]
    rule = _RecordingDownpour()
    ps = ParameterServer(center, rule, K)
    barrier = threading.Barrier(K)

    def worker(i):
        for _ in range(N):
            barrier.wait()  # maximize contention → real batches form
            ps.commit(i, deltas[i])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(K)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(rule.order) == K * N
    # replay the recorded arrival order serially on a twin
    twin = ParameterServer(center, DownpourMerge(), K)
    for tag in rule.order:
        twin.commit(tag, deltas[tag])
    assert _tree_equal(ps.get_model(), twin.get_model())
    assert ps.num_updates == twin.num_updates == K * N


def test_batched_drain_folds_k_commits_in_one_acquisition():
    """Deterministic flat-combining: with the center lock held, K
    commits queue up; the release lets ONE leader drain all K — the
    batched_folds stat records K and the lock was acquired once for
    the whole batch."""
    K = 4
    center = {"w": np.zeros(64, np.float32)}
    ps = ParameterServer(center, DownpourMerge(), K)
    delta = {"w": np.ones(64, np.float32)}
    assert ps._lock.acquire()
    acq_before = ps._lock.acquires
    threads = [
        threading.Thread(target=ps.commit, args=(i, delta))
        for i in range(K)
    ]
    try:
        for t in threads:
            t.start()
        deadline = time.monotonic() + 10
        while len(ps._fold_pending) < K and time.monotonic() < deadline:
            time.sleep(0.001)
        assert len(ps._fold_pending) == K
    finally:
        ps._lock.release()
    for t in threads:
        t.join(timeout=10)
    s = ps.stats()
    assert s["commits"] == K
    assert s["batched_folds"] == K
    # one drain acquisition for all K folds, plus stray empty re-checks:
    # the protocol legally allows EVERY follower one stray acquire (its
    # 0.5 ms wait slice can expire during the leader's drain and lose
    # the race to its own done-event — seen under full-suite load, the
    # ISSUE 14 jitter-hardening pass), so the bound is 1 + (K-1) = K.
    # The batching claim itself is batched_folds == K above.
    assert ps._lock.acquires - acq_before <= K
    assert np.array_equal(ps.center["w"], np.full(64, K, np.float32))


def test_shm_concurrent_stress_four_workers_exact():
    """4 workers hammering fused exchanges over the rings with integer
    deltas: the final center is exact (order-independent in integer
    arithmetic — any fold-order bug shows), counters agree, nothing
    leaks."""
    W, N = 4, 20
    before = set(_dkshm_entries())
    center = {"w": np.zeros(2048, np.float32)}
    delta = {"w": np.ones(2048, np.float32)}
    ps = ShmParameterServer(center, DownpourMerge(), W, ring_bytes=1 << 16)
    ps.initialize()
    ps.start()
    clients = [ShmPSClient(ps, i) for i in range(W)]
    errors = []

    def worker(i):
        try:
            c = clients[i]
            c.pull()
            for _ in range(N):
                out = c.exchange(i, delta)
                assert float(out["w"][0]) == float(out["w"][-1])
        except BaseException as e:
            errors.append(e)

    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(W)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        s = ps.stats()
        assert s["commits"] == W * N
        assert s["fused_exchanges"] == W * N
        assert s["batched_folds"] >= 0  # host-dependent; key present
        assert np.array_equal(ps.center["w"],
                              np.full(2048, W * N, np.float32))
    finally:
        for c in clients:
            c.close()
        ps.stop()
    assert set(_dkshm_entries()) <= before


# -- native lane parity ------------------------------------------------------


def test_native_shm_lane_parity():
    """The dkps.cpp ring lane: a shm-connected native client speaks the
    full protocol (pull/commit/exchange/heartbeat/join) and sees the
    same center as a TCP client of the same server."""
    from distkeras_tpu.native import load_dkps

    if load_dkps(required=False) is None:
        pytest.skip("no C++ toolchain")
    from distkeras_tpu.native_ps import (
        NativePSClient,
        NativeSocketParameterServer,
    )

    before = set(_dkshm_entries())
    center = {"w": np.zeros(4096, np.float32)}
    ps = NativeSocketParameterServer(center, DownpourMerge(), 2)
    ps.initialize()
    ps.start()
    try:
        c = NativePSClient.connect_shm(ps, 0)
        assert np.array_equal(c.pull()["w"], center["w"])
        delta = {"w": np.full(4096, 1.5, np.float32)}
        c.commit(0, delta)
        out = c.exchange(0, delta, seq=2)
        assert np.allclose(out["w"], 3.0)
        assert c.heartbeat() in (True, False)
        tcp = NativePSClient("127.0.0.1", ps.port, 1, ps.spec)
        assert np.allclose(tcp.pull()["w"], 3.0)  # one center, two lanes
        tcp.close()
        c.close()
    finally:
        ps.stop()
    assert set(_dkshm_entries()) <= before  # native segments unlink too


# -- trainer validation matrix ----------------------------------------------


def test_shm_transport_validation_matrix():
    """ps_transport='shm' is colocated-only: ps_host rejected with an
    actionable error; the standby/chain replication rules keep pointing
    at socket; the constructor accepts the plain shm config."""
    import distkeras_tpu as dk

    from tests.test_trainers import model_spec

    def mk(**kw):
        return dk.DOWNPOUR(model_spec(), backend="ps",
                           ps_transport="shm", num_workers=1, **kw)

    mk()  # plain shm config is valid
    mk(ps_num_shards=2)  # sharded shm is valid
    with pytest.raises(ValueError, match="colocated-only"):
        mk(ps_host="10.0.0.1")
    with pytest.raises(ValueError, match="socket"):
        mk(ps_standby=True)
    with pytest.raises(ValueError, match="socket"):
        mk(ps_chain_length=2)
    with pytest.raises(ValueError, match="socket"):
        from distkeras_tpu.resilience import FaultPlan

        mk(ps_wal_dir="/tmp/x", fault_plan=FaultPlan(
            seed=0, kill_ps_after_commits=1))
    with pytest.raises(ValueError, match="shm"):
        dk.DOWNPOUR(model_spec(), backend="ps", ps_transport="bogus")
    # and the server itself refuses replication streams
    ps = ShmParameterServer({"w": np.zeros(4, np.float32)},
                            DownpourMerge(), 1)
    with pytest.raises(NotImplementedError, match="colocated-only"):
        ps.attach_standby("127.0.0.1", 1)
