"""Int8 weight-only quantization: ops.quant + the LM serving path.

Beyond-reference (SURVEY.md §2b #15 covers float serving only). The
kernel-level contracts: symmetric per-channel quantization error is
bounded by half a step, the XLA lowering equals the exact dequantized
matmul, and the Pallas kernel (interpret mode here, real on TPU) equals
the XLA lowering. The model-level contract: quantize_lm preserves the
architecture (param structure pins against the quant module's own init)
and the decode path produces near-identical generations.
"""

import chex
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops.quant import (
    QTensor,
    dequantize,
    q_matmul,
    quantize,
    quantize_dense_tree,
)


def test_quantize_roundtrip_error_bound(rng):
    w = rng.normal(size=(64, 96)).astype(np.float32)
    qt = quantize(w)
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (96,)
    deq = np.asarray(dequantize(qt))
    step = np.asarray(qt.scale)
    assert np.all(np.abs(deq - w) <= 0.5 * step[None, :] + 1e-7)


def test_quantize_zero_channel_is_exact(rng):
    w = rng.normal(size=(8, 4)).astype(np.float32)
    w[:, 2] = 0.0  # absmax 0 would divide by zero without the guard
    qt = quantize(w)
    deq = np.asarray(dequantize(qt))
    np.testing.assert_array_equal(deq[:, 2], 0.0)


def test_q_matmul_xla_matches_exact_dequant(rng):
    w = rng.normal(size=(128, 256)).astype(np.float32)
    x = rng.normal(size=(4, 128)).astype(np.float32)
    qt = quantize(w)
    got = np.asarray(q_matmul(jnp.asarray(x), qt, impl="xla"))
    want = x @ np.asarray(dequantize(qt))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("lead", [(1,), (5,), (2, 3)])
def test_q_matmul_pallas_matches_xla(rng, lead):
    w = rng.normal(size=(256, 384)).astype(np.float32)
    qt = quantize(w)
    x = rng.normal(size=lead + (256,)).astype(np.float32)
    a = np.asarray(q_matmul(jnp.asarray(x), qt, impl="pallas",
                            interpret=True))
    b = np.asarray(q_matmul(jnp.asarray(x), qt, impl="xla"))
    assert a.shape == lead + (384,)
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_q_matmul_auto_falls_back_on_untileable_shapes(rng):
    w = rng.normal(size=(100, 96)).astype(np.float32)  # K%128 != 0
    x = rng.normal(size=(3, 100)).astype(np.float32)
    qt = quantize(w)
    got = np.asarray(q_matmul(jnp.asarray(x), qt))  # auto → xla, no error
    want = x @ np.asarray(dequantize(qt))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="multiples"):
        q_matmul(jnp.asarray(x), qt, impl="pallas")


def test_quantize_dense_tree_converts_only_dense_pairs(rng):
    tree = {
        "dense": {"kernel": rng.normal(size=(8, 4)).astype(np.float32),
                  "bias": np.zeros(4, np.float32)},
        "ln": {"scale": np.ones(8, np.float32),
               "bias": np.zeros(8, np.float32)},
        "embed": {"embedding": rng.normal(size=(16, 8)).astype(np.float32)},
    }
    out = quantize_dense_tree(tree)
    assert set(out["dense"]) == {"kernel_q", "scale", "bias"}
    assert out["dense"]["kernel_q"].dtype == jnp.int8
    assert set(out["ln"]) == {"scale", "bias"}          # untouched
    assert set(out["embed"]) == {"embedding"}           # untouched


@pytest.fixture(scope="module")
def lm_pair():
    """A small f32 LM + its int8 quantization (module-scoped: compile once)."""
    from distkeras_tpu.models import quantize_lm, transformer_lm

    spec = transformer_lm(vocab=64, maxlen=32, dim=64, heads=4, depth=2,
                          dtype=jnp.float32)
    params, _ = spec.init_np(3)
    qspec, qparams = quantize_lm(spec, params)
    return spec, params, qspec, qparams


def test_quantize_lm_param_structure_matches_quant_module(lm_pair):
    _, _, qspec, qparams = lm_pair
    # the converted tree must be exactly what the quant=True module expects
    q0, _ = qspec.init_np(0)
    chex.assert_trees_all_equal_structs(q0, qparams)
    jax.tree.map(lambda a, b: chex.assert_equal_shape((a, b)), q0,
                 jax.tree.map(jnp.asarray, qparams))


def test_quantize_lm_logits_track_fp32(lm_pair, rng):
    spec, params, qspec, qparams = lm_pair
    tokens = jnp.asarray(rng.integers(0, 64, size=(2, 32)), jnp.int32)
    base, _ = spec.apply(params, {}, tokens, False)
    qout, _ = qspec.apply(qparams, {}, tokens, False)
    rel = (np.linalg.norm(np.asarray(qout) - np.asarray(base))
           / np.linalg.norm(np.asarray(base)))
    assert rel < 0.05, f"int8 logits diverged: rel error {rel:.4f}"


def test_quantized_generate_matches_fp32_greedy(lm_pair, rng):
    from distkeras_tpu.models import generate

    spec, params, qspec, qparams = lm_pair
    prompt = jnp.asarray(rng.integers(0, 64, size=(2, 8)), jnp.int32)
    base = generate(spec, params, prompt, max_new_tokens=16)
    qout = generate(qspec, qparams, prompt, max_new_tokens=16)
    assert qout.shape == base.shape == (2, 24)
    agree = float(np.mean(base[:, 8:] == qout[:, 8:]))
    # greedy decode over near-identical logits: occasional argmax flips at
    # ties are expected, wholesale divergence is not
    assert agree >= 0.75, f"greedy agreement only {agree:.2f}"


def test_qdense_keeps_activation_dtype_bf16():
    """A bf16 quantized model must stay bf16 through QDense: the trained
    f32 bias is cast before the add, matching nn.Dense(dtype=bf16) — a
    bare f32 add would promote every downstream tensor."""
    from distkeras_tpu.models.lm import QDense

    mod = QDense(features=128, dtype=jnp.bfloat16)
    params = mod.init(jax.random.PRNGKey(0), jnp.zeros((2, 128), jnp.bfloat16))
    params = {"params": {**params["params"],
                         "bias": np.zeros(128, np.float32)}}  # trained-style
    out = mod.apply(params, jnp.ones((2, 128), jnp.bfloat16))
    assert out.dtype == jnp.bfloat16


def test_quantize_lm_rejects_double_quant(lm_pair):
    from distkeras_tpu.models import quantize_lm

    _, _, qspec, qparams = lm_pair
    with pytest.raises(ValueError, match="already quantized"):
        quantize_lm(qspec, qparams)


def test_generator_predictor_serves_quantized_lm(lm_pair, rng):
    from distkeras_tpu.data import Dataset
    from distkeras_tpu.predictors import GeneratorPredictor

    _, _, qspec, qparams = lm_pair
    prompts = rng.integers(0, 64, size=(5, 8)).astype(np.int32)
    ds = Dataset({"features": prompts})
    out = GeneratorPredictor(qspec, qparams, max_new_tokens=4,
                             batch_size=4).predict(ds)
    assert out["generated"].shape == (5, 4)
    assert out["generated"].dtype == np.int32


# -- generic serving path (quantize_serving / ModelPredictor) ---------------


def test_quantize_serving_mlp_logits_track_fp32(rng):
    from distkeras_tpu.models import mlp
    from distkeras_tpu.ops.quant import quantize_serving

    spec = mlp(input_shape=(16,), hidden=(64, 32), num_classes=4,
               dtype=jnp.float32)
    params, state = spec.init_np(1)
    x = rng.normal(size=(8, 16)).astype(np.float32)
    base, _ = spec.apply(params, state, x, False)
    qspec, qparams = quantize_serving(spec, params)
    assert qspec.name.endswith("_int8")
    qout, _ = qspec.apply(qparams, state, x, False)
    rel = (np.linalg.norm(np.asarray(qout) - np.asarray(base))
           / np.linalg.norm(np.asarray(base)))
    assert rel < 0.05, rel


@pytest.mark.slow  # classifier serving integration; lm logits-tracking pin stays fast
def test_quantize_serving_transformer_classifier(rng):
    """The interceptor reaches Dense layers created inside functional
    sublayers (named qkv/attn_out/mlp_up/mlp_down) too."""
    from distkeras_tpu.models import transformer_classifier
    from distkeras_tpu.ops.quant import quantize_serving

    spec = transformer_classifier(vocab=64, maxlen=16, dim=64, heads=4,
                                  depth=2, num_classes=3,
                                  dtype=jnp.float32)
    params, state = spec.init_np(2)
    tok = jnp.asarray(rng.integers(0, 64, size=(4, 16)), jnp.int32)
    base, _ = spec.apply(params, state, tok, False)
    qspec, qparams = quantize_serving(spec, params)
    # every Dense kernel in the tree was actually converted
    import jax

    flat = jax.tree_util.tree_flatten_with_path(qparams)[0]
    q_leaves = [p for p, v in flat
                if getattr(v, "dtype", None) == jnp.int8]
    assert len(q_leaves) >= 2 * 4 + 1  # 4 Dense/block x 2 blocks + head
    qout, _ = qspec.apply(qparams, state, tok, False)
    rel = (np.linalg.norm(np.asarray(qout) - np.asarray(base))
           / np.linalg.norm(np.asarray(base)))
    assert rel < 0.05, rel


def test_quantize_serving_rejects_training():
    from distkeras_tpu.models import mlp
    from distkeras_tpu.ops.quant import quantize_serving

    spec = mlp(input_shape=(8,), hidden=(16,), num_classes=2,
               dtype=jnp.float32)
    params, state = spec.init_np(0)
    qspec, qparams = quantize_serving(spec, params)
    with pytest.raises(ValueError, match="serving path"):
        qspec.apply(qparams, state, jnp.zeros((2, 8)), True)


def test_model_predictor_quantize_agrees_with_fp(rng):
    """End-to-end serving parity: int8 predictions agree with fp on
    well-separated inputs (trained-ish weights via a quick fit)."""
    from distkeras_tpu import SingleTrainer
    from distkeras_tpu.predictors import LabelIndexPredictor
    from tests.test_trainers import blobs_dataset, model_spec

    ds = blobs_dataset(n=1024)
    t = SingleTrainer(model_spec(), loss="sparse_softmax_cross_entropy",
                      worker_optimizer="sgd", learning_rate=0.1,
                      batch_size=32, num_epoch=3)
    t.train(ds, shuffle=True)
    test = blobs_dataset(n=256, seed=9)
    fp = LabelIndexPredictor(
        t.spec, t.trained_params_, state=t.trained_nt_, batch_size=64
    ).predict(test)
    q = LabelIndexPredictor(
        t.spec, t.trained_params_, state=t.trained_nt_, batch_size=64,
        quantize=True,
    ).predict(test)
    agree = float(np.mean(fp["prediction"] == q["prediction"]))
    assert agree >= 0.98, agree


def test_quantize_serving_only_touches_real_dense(rng):
    """The recording trace protects non-Dense kernel/bias modules: a
    DenseGeneral stays float (and working), and a bias-less nn.Dense DOES
    quantize — both in one model."""
    import flax.linen as nn

    from distkeras_tpu.model import from_flax
    from distkeras_tpu.ops.quant import quantize_serving

    class Mixed(nn.Module):
        @nn.compact
        def __call__(self, x, training: bool = False):
            x = nn.Dense(32, use_bias=False, name="nobias")(x)
            x = nn.relu(x)
            x = nn.DenseGeneral(16, name="general")(x)
            return nn.Dense(4, name="out")(x)

    spec = from_flax(Mixed(), jnp.zeros((1, 8), jnp.float32))
    params, state = spec.init_np(0)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    base, _ = spec.apply(params, state, x, False)
    qspec, qparams = quantize_serving(spec, params)
    assert set(qparams["nobias"]) == {"kernel_q", "scale"}   # quantized
    assert set(qparams["out"]) == {"kernel_q", "scale", "bias"}
    assert set(qparams["general"]) == {"kernel", "bias"}     # untouched
    qout, _ = qspec.apply(qparams, state, x, False)          # and it runs
    rel = (np.linalg.norm(np.asarray(qout) - np.asarray(base))
           / (np.linalg.norm(np.asarray(base)) + 1e-9))
    assert rel < 0.05, rel


def test_quantize_serving_rejects_specless_models():
    from distkeras_tpu.model import ModelSpec
    from distkeras_tpu.ops.quant import quantize_serving

    spec = ModelSpec(init=lambda k: ({}, {}),
                     apply=lambda p, s, x, t: (x, s))
    with pytest.raises(ValueError, match="flax-backed"):
        quantize_serving(spec, {})


def test_quantize_serving_handles_keyword_invocation(rng):
    """Dense called as Dense(...)(inputs=x) quantizes AND serves."""
    import flax.linen as nn

    from distkeras_tpu.model import from_flax
    from distkeras_tpu.ops.quant import quantize_serving

    class KW(nn.Module):
        @nn.compact
        def __call__(self, x, training: bool = False):
            return nn.Dense(4, name="d")(inputs=x)

    spec = from_flax(KW(), jnp.zeros((1, 8), jnp.float32))
    params, state = spec.init_np(0)
    qspec, qparams = quantize_serving(spec, params)
    assert set(qparams["d"]) == {"kernel_q", "scale", "bias"}
    x = rng.normal(size=(3, 8)).astype(np.float32)
    base, _ = spec.apply(params, state, x, False)
    qout, _ = qspec.apply(qparams, state, x, False)
    np.testing.assert_allclose(np.asarray(qout), np.asarray(base),
                               rtol=0.05, atol=0.05)


def test_single_trainer_accepts_ema_and_prefetch():
    from distkeras_tpu import SingleTrainer
    from tests.test_trainers import blobs_dataset, model_spec

    t = SingleTrainer(model_spec(), loss="sparse_softmax_cross_entropy",
                      worker_optimizer="sgd", learning_rate=0.1,
                      batch_size=32, num_epoch=1, ema_decay=0.0, prefetch=2)
    params = t.train(blobs_dataset(n=256))
    assert t.ema_params_ is not None
    for la, lb in zip(jax.tree.leaves(t.ema_params_),
                      jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
