"""Test configuration: fake 8-device CPU mesh.

The reference's only "distributed without a cluster" mechanism was Spark
``local[N]`` (SURVEY.md §4). The TPU analogue is XLA's forced host platform
device count: 8 fake CPU devices give every trainer's collective path a real
mesh in CI, no TPU required. Must be set before JAX is imported.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("KERAS_BACKEND", "jax")

# Force CPU regardless of any TPU platform the outer env selects (a TPU
# plugin may already be registered by a sitecustomize hook before this
# conftest runs, so the switch must go through jax.config, not env vars).
import jax

jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# NO persistent compile cache for the test suite: this jaxlib (0.4.37,
# XLA:CPU) aborts the whole process (SIGSEGV/SIGABRT) when certain
# 8-device sharded executables are RELOADED from the persistent cache —
# observed on the FSDP and megatron-TP run_step programs; a warm-cache
# tier-1 run died at the first such reload, losing every test after it.
# Cold compiles are fine and the full suite fits the CI budget without
# the cache, so determinism wins. (bench.py keeps its own repo-local
# cache: its single-device programs don't hit the bug.)
import jax as _jax

_jax.config.update("jax_enable_compilation_cache", False)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
