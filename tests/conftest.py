"""Test configuration: fake 8-device CPU mesh.

The reference's only "distributed without a cluster" mechanism was Spark
``local[N]`` (SURVEY.md §4). The TPU analogue is XLA's forced host platform
device count: 8 fake CPU devices give every trainer's collective path a real
mesh in CI, no TPU required. Must be set before JAX is imported.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("KERAS_BACKEND", "jax")

# Force CPU regardless of any TPU platform the outer env selects (a TPU
# plugin may already be registered by a sitecustomize hook before this
# conftest runs, so the switch must go through jax.config, not env vars).
import jax

jax.config.update("jax_platforms", "cpu")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Persistent compile cache: identical programs (shared model configs across
# tests, reruns of either tier) skip XLA compilation — the dominant cost on
# this 1-core CI host. One code path with the user-facing helper.
import tempfile

from distkeras_tpu.utils import enable_compilation_cache

enable_compilation_cache(os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(tempfile.gettempdir(), "distkeras-jax-test-cache"),
))

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
