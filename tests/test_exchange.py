"""ISSUE 10: the fused commit+pull EXCHANGE and the pipelined window loop.

Pins, per the acceptance criteria:

- the fused exchange is semantically the ``commit(); pull()`` pair in ONE
  round trip (counters: ``exchange_rtts`` == windows + initial pulls, not
  2×windows), on every transport;
- a lost-ACK replay of a fused exchange never double-folds NOR advances
  the fold count twice (the pull half replays like a retried pull);
- ``ps_pipeline_depth=0`` (the default) is bit-identical to the
  pre-fusion HEAD path for ADAG/DOWNPOUR/DynSGD, int8 and 2-shard legs
  included, and depth 1 is bit-identical to depth 0 for the single
  DOWNPOUR worker (the deferred re-base telescopes exactly);
- the pipelined exchange's one-window staleness is PRICED into DynSGD τ
  (the ``lag`` flag reads the previous pull version);
- a cleanly drained elastic-rule (EASGD) worker commits its final
  elastic difference instead of abandoning its variable mid-epoch.
"""

import threading
import warnings

import numpy as np
import pytest

from distkeras_tpu.parallel.merge_rules import (
    DownpourMerge,
    DynSGDMerge,
)
from distkeras_tpu.parameter_servers import (
    ParameterServer,
    ParameterServerClient,
    SocketParameterServer,
)
from tests.test_trainers import blobs_dataset, final_loss, model_spec


def _tree_equal(a, b):
    import jax

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


# -- the fused action, unit level --------------------------------------------


def test_inprocess_exchange_is_commit_plus_pull():
    center = {"w": np.zeros(3, np.float32)}
    ps = ParameterServer(center, DownpourMerge(), num_workers=2)
    ps.pull(0)
    out, applied = ps.exchange(0, {"w": np.ones(3, np.float32)})
    assert applied
    assert np.array_equal(out["w"], np.ones(3, np.float32))  # post-fold
    assert ps.num_updates == 1
    assert ps._pull_versions[0] == 1  # fused pull recorded post-fold
    s = ps.stats()
    # one fused op counts one commit AND one pull but ONE round trip
    assert s["fused_exchanges"] == 1
    assert s["commits"] == 1 and s["pulls"] == 2
    assert s["exchange_rtts"] == 2  # initial pull + one fused exchange


def test_exchange_dup_replay_no_double_fold_or_double_advance():
    """The lost-ACK replay contract: same seq → the fold is skipped (no
    double-fold, num_updates advances once) while the pull half answers
    with a fresh center and records its version exactly as a retried
    standalone pull would — never past ``num_updates``."""
    center = {"w": np.zeros(2, np.float32)}
    ps = ParameterServer(center, DownpourMerge(), num_workers=1)
    ps.pull(0)
    d = {"w": np.ones(2, np.float32)}
    out1, applied1 = ps.exchange(0, d, seq=1)
    n_after = ps.num_updates
    out2, applied2 = ps.exchange(0, d, seq=1)  # the replay
    assert applied1 and not applied2
    assert ps.num_updates == n_after == 1          # folded exactly once
    assert ps._pull_versions[0] == ps.num_updates  # not double-advanced
    assert np.array_equal(out2["w"], out1["w"])    # fresh center returned
    s = ps.stats()
    assert s["dup_commits"] == 1 and s["fused_exchanges"] == 2
    assert s["num_updates"] == 1


def test_exchange_lag_prices_previous_pull_version():
    """DynSGD under the pipelined lag flag: the delta committed at
    exchange N was computed from the center of exchange N−1, so τ must
    be measured from the PREVIOUS recorded pull version — one extra
    window of staleness, priced, not hidden."""
    ps = ParameterServer({"w": np.zeros(1, np.float32)}, DynSGDMerge(),
                         num_workers=1)
    ps.pull(0)                                       # v0 = 0
    d = {"w": np.array([2.0], np.float32)}
    ps.exchange(0, d, lag=True)   # prev unset → cur v0: τ=0 → +2.0
    ps.exchange(0, d, lag=True)   # prev=v0=0, updates=1: τ=1 → +1.0
    assert np.allclose(ps.get_model()["w"], 3.0)
    # the UN-lagged exchange would have priced τ=0 (+2.0): the flag is
    # exactly one window of extra staleness
    ps2 = ParameterServer({"w": np.zeros(1, np.float32)}, DynSGDMerge(),
                          num_workers=1)
    ps2.pull(0)
    ps2.exchange(0, d)
    ps2.exchange(0, d)
    assert np.allclose(ps2.get_model()["w"], 4.0)


def test_socket_exchange_matches_inprocess_bitwise():
    rng = np.random.default_rng(3)
    center = {"w": rng.normal(size=(64,)).astype(np.float32)}
    deltas = [{"w": rng.normal(size=(64,)).astype(np.float32) * 0.1}
              for _ in range(4)]
    ref = ParameterServer(center, DynSGDMerge(), num_workers=1)
    ref.pull(0)
    for d in deltas:
        ref.exchange(0, d, lag=True)

    ps = SocketParameterServer(center, DynSGDMerge(), num_workers=1)
    ps.initialize()
    ps.start()
    try:
        c = ParameterServerClient("127.0.0.1", ps.port, 0)
        c.pull()
        out = None
        for d in deltas:
            out = c.exchange(0, d, lag=True)
        assert _tree_equal(ps.get_model(), ref.get_model())
        assert _tree_equal(out, ref.get_model())
        c.close()
    finally:
        ps.stop()


def test_native_exchange_matches_python_bitwise():
    from distkeras_tpu.native import load_dkps

    if load_dkps() is None:
        pytest.skip("no C++ toolchain to build libdkps")
    from distkeras_tpu.native_ps import (
        NativePSClient,
        NativeSocketParameterServer,
    )

    rng = np.random.default_rng(5)
    center = {"w": rng.normal(size=(96,)).astype(np.float32)}
    deltas = [{"w": rng.normal(size=(96,)).astype(np.float32) * 0.1}
              for _ in range(4)]
    ref = ParameterServer(center, DynSGDMerge(), num_workers=1)
    ref.pull(0)
    ref.exchange(0, deltas[0], seq=1, lag=True)
    ref.exchange(0, deltas[1], seq=2, lag=True)
    ref.exchange(0, deltas[1], seq=2, lag=True)  # dup replay
    ref.exchange(0, deltas[2], seq=3, lag=True)

    ps = NativeSocketParameterServer(center, DynSGDMerge(), num_workers=1)
    ps.initialize()
    ps.start()
    try:
        c = NativePSClient("127.0.0.1", ps.port, 0, ps.spec)
        c.pull()
        c.exchange(0, deltas[0], seq=1, lag=True)
        c.exchange(0, deltas[1], seq=2, lag=True)
        out_dup = c.exchange(0, deltas[1], seq=2, lag=True)  # dup replay
        c.exchange(0, deltas[2], seq=3, lag=True)
        assert _tree_equal(ps.get_model(), ref.get_model())
        assert ps.num_updates == ref.num_updates == 3
        # the dup returned the then-current center, not a re-fold
        assert np.all(np.isfinite(out_dup["w"]))
        s = ps.stats()
        assert s["fused_exchanges"] == 4 and s["dup_commits"] == 1
        c.close()
    finally:
        ps.stop()


def test_fused_exchange_chaos_exactly_once():
    """The acceptance chaos oracle: fused exchanges under seeded wire
    drops (the recv drop — server folded, reply died, client replays)
    keep the dedup exactly-once: lifetime folds == logical exchanges
    confirmed, and no worker's pull version runs past the fold count."""
    from distkeras_tpu.resilience.faults import FaultPlan
    from distkeras_tpu.resilience.retry import (
        ResilientPSClient,
        RetryPolicy,
    )

    W, N = 2, 15
    center = {"w": np.zeros(128, np.float32)}
    delta = {"w": np.full(128, 1e-3, np.float32)}
    ps = SocketParameterServer(center, DownpourMerge(), num_workers=W)
    ps.initialize()
    ps.start()
    policy = RetryPolicy(max_attempts=50, base_delay=0.005,
                         max_delay=0.05, deadline=60.0)
    clients = [
        ResilientPSClient(
            lambda i=i: ParameterServerClient("127.0.0.1", ps.port, i),
            i, policy=policy,
        )
        for i in range(W)
    ]
    plan = FaultPlan(seed=11, drop_recv=0.12, max_faults=60)
    errors = []

    def worker(i):
        try:
            c = clients[i]
            c.pull()
            for _ in range(N):
                out = c.exchange(i, delta)
                assert np.all(np.isfinite(out["w"]))
        except BaseException as e:  # surfaced below
            errors.append(e)

    try:
        with plan:
            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(W)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert not errors, errors
        assert plan.stats()["drops"] > 0  # the chaos actually bit
        logical = sum(c.seq for c in clients)
        assert logical == W * N
        assert ps.num_updates == logical  # exactly-once folds
        for i in range(W):
            assert ps._pull_versions[i] <= ps.num_updates
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        ps.stop()


def test_socket_exchange_wire_frame_wal_replay(tmp_path):
    """A durable socket exchange logs its request frame VERBATIM
    (REC_COMMIT_WIRE) plus the fused pull record; recovery replays both
    through the live decode pipeline to a bit-identical server."""
    rng = np.random.default_rng(9)
    center = {"w": rng.normal(size=(32,)).astype(np.float32)}
    deltas = [{"w": rng.normal(size=(32,)).astype(np.float32) * 0.1}
              for _ in range(3)]
    ps = SocketParameterServer(center, DynSGDMerge(), num_workers=1,
                               wal_dir=str(tmp_path / "wal"),
                               wal_group_window=1)
    ps.initialize()
    ps.start()
    try:
        c = ParameterServerClient("127.0.0.1", ps.port, 0)
        c.pull()
        for i, d in enumerate(deltas):
            c.exchange(0, d, seq=i + 1, lag=True)
        live_center = ps.get_model()
        live_cur = dict(ps._pull_versions)
        live_prev = dict(ps._prev_pull_versions)
        c.close()
    finally:
        ps.stop()
    rec = ParameterServer(center, DynSGDMerge(), num_workers=1,
                          wal_dir=str(tmp_path / "wal"))
    assert rec.recovered_ and rec.num_updates == 3
    assert _tree_equal(rec.get_model(), live_center)
    assert rec._pull_versions == live_cur
    assert rec._prev_pull_versions == live_prev
    rec.stop()


def test_wal_recovery_restores_prev_pull_versions(tmp_path):
    """A recovered server continues lag-pricing exactly where the crashed
    one left off: the prev-pull-version map is reconstructed by replaying
    the same cur→prev shift the live server runs."""
    center = {"w": np.zeros(8, np.float32)}
    d = {"w": np.ones(8, np.float32)}
    ps = ParameterServer(center, DynSGDMerge(), num_workers=1,
                         wal_dir=str(tmp_path / "wal"), wal_group_window=1)
    ps.pull(0)
    ps.exchange(0, d, lag=True)
    ps.exchange(0, d, lag=True)
    prev, cur = dict(ps._prev_pull_versions), dict(ps._pull_versions)
    ps.stop()

    twin = ParameterServer(center, DynSGDMerge(), num_workers=1)
    twin.pull(0)
    twin.exchange(0, d, lag=True)
    twin.exchange(0, d, lag=True)

    rec = ParameterServer(center, DynSGDMerge(), num_workers=1,
                          wal_dir=str(tmp_path / "wal"))
    assert rec.recovered_
    assert rec._prev_pull_versions == prev
    assert rec._pull_versions == cur
    assert _tree_equal(rec.get_model(), twin.get_model())
    # the continued run prices identically to the no-crash twin
    rec.exchange(0, d, lag=True)
    twin.exchange(0, d, lag=True)
    assert _tree_equal(rec.get_model(), twin.get_model())
    rec.stop()


# -- trainer-level bit-identity (the depth-0 acceptance pin) -----------------


def _run(cls_name, **kw):
    import distkeras_tpu as dk

    ds = blobs_dataset(n=512)
    kw.setdefault("learning_rate", 0.05)
    t = getattr(dk, cls_name)(
        model_spec(), loss="sparse_softmax_cross_entropy",
        worker_optimizer="sgd", num_workers=kw.pop("num_workers", 1),
        batch_size=16, communication_window=2, num_epoch=2,
        backend="ps", **kw,
    )
    weights = t.train(ds, shuffle=False)
    return t, weights


@pytest.mark.parametrize("cls_name", ["ADAG", "DOWNPOUR", "DynSGD"])
def test_fused_depth0_bit_identical_to_unfused(cls_name):
    """pipeline_depth=0 with the fused wire action is bit-identical to
    the HEAD commit();pull() path (ps_fused_exchange=False IS that
    path), per merge rule."""
    _, w_head = _run(cls_name, ps_fused_exchange=False)
    _, w_fused = _run(cls_name)
    assert _tree_equal(w_head, w_fused)


def test_fused_depth0_bit_identical_int8_leg():
    _, w_head = _run("DOWNPOUR", compression="int8",
                     pull_compression="int8", ps_fused_exchange=False)
    _, w_fused = _run("DOWNPOUR", compression="int8",
                      pull_compression="int8")
    assert _tree_equal(w_head, w_fused)


def test_fused_depth0_bit_identical_two_shard_leg():
    _, w_head = _run("DynSGD", ps_num_shards=2, ps_transport="socket",
                     ps_fused_exchange=False)
    t, w_fused = _run("DynSGD", ps_num_shards=2, ps_transport="socket")
    assert _tree_equal(w_head, w_fused)
    # every shard served its windows as ONE round trip each
    for s in t.ps_stats_["per_shard"]:
        assert s["fused_exchanges"] == s["commits"]
        assert s["exchange_rtts"] == s["commits"] + s["pulls"] \
            + s["compressed_pulls"] + s["dup_commits"] \
            - s["fused_exchanges"]


@pytest.mark.parametrize("codec", [None, "int8"])
def test_pipelined_downpour_bit_identical_to_serial(codec):
    """The single DOWNPOUR worker's depth-1 loop telescopes exactly:
    C_N == C_{N-1} + sent_N at fold scale 1, so the deferred re-base
    reproduces the serial trajectory bit-for-bit — raw AND int8-commit
    legs. (int8 PULL compression is excluded by construction: each
    compressed pull is individually lossy, so the serial loop's re-base
    onto ``decode(pull_N)`` and the pipelined ``decode(pull_{N-1}) +
    sent_N`` legitimately differ below the quantization step — the EF
    stream still telescopes on both.)"""
    kw = {}
    if codec:
        kw = dict(compression=codec)
    _, w0 = _run("DOWNPOUR", **kw)
    _, w1 = _run("DOWNPOUR", ps_pipeline_depth=1, **kw)
    assert _tree_equal(w0, w1)


def test_pipelined_exchange_carries_lag_flag(monkeypatch):
    """Depth 1 must price its one-window staleness: every exchange the
    pipelined loop issues carries lag=True; the serial loop's never do."""
    from distkeras_tpu import workers as workers_mod

    seen = []
    orig = workers_mod._BoundPS.exchange

    def spy(self, worker_id, payload, seq=None, lag=False):
        seen.append(lag)
        return orig(self, worker_id, payload, seq=seq, lag=lag)

    monkeypatch.setattr(workers_mod._BoundPS, "exchange", spy)
    _run("DynSGD", ps_pipeline_depth=1)
    assert seen and all(seen)
    seen.clear()
    _run("DynSGD")
    assert seen and not any(seen)


def test_trainer_rtt_counters_fused_vs_serial():
    """The acceptance counter oracle from a real training run: with
    fusion, exchange_rtts == windows + initial pulls (1 RTT per window);
    without, 2×windows + initial pulls."""
    W = 2
    t_fused, _ = _run("DOWNPOUR", num_workers=W, ps_transport="socket")
    s = t_fused.ps_stats_
    windows = s["commits"]  # counted pre-ACK: exact by run end
    assert windows > 0
    # EXACT counters (ISSUE 11): pull-side counts still land after the
    # reply send (delivered-traffic semantics), but stats() now runs the
    # settling barrier — it waits for every in-flight reply window to
    # close before reading — so the historical ≤1-per-worker tolerance
    # is gone
    assert s["fused_exchanges"] == windows
    assert s["exchange_rtts"] == windows + 2
    t_head, _ = _run("DOWNPOUR", num_workers=W, ps_transport="socket",
                     ps_fused_exchange=False)
    sh = t_head.ps_stats_
    assert sh["fused_exchanges"] == 0
    assert sh["exchange_rtts"] == 2 * sh["commits"] + 2
    # the per-phase timing proof rides ps_stats_ on every transport:
    # fused runs never paid a standalone pull after the initial one
    phases = t_fused.ps_stats_["exchange_phases"]
    assert phases["commit"]["count"] == windows
    assert "pull" not in phases
    assert t_head.ps_stats_["exchange_phases"]["pull"]["count"] \
        == sh["commits"]


def test_pipelined_elastic_exactly_once_under_membership_chaos():
    """Depth-1 elastic loop: block confirmation rides the DEFERRED
    exchange ACK, and the exactly-once ledger survives a live join and a
    preemption drain mid-run."""
    import distkeras_tpu as dk
    from distkeras_tpu.resilience.faults import FaultPlan

    ds = blobs_dataset(n=512)
    # threshold-1 events (>= semantics), the test_elastic treatment: a
    # live worker always completes >= 1 window (peers wait on its claimed
    # block), so the events fire even when a 1-core host lets the other
    # workers drain the pool first
    plan = FaultPlan(seed=7, join_worker_at_window={0: 1},
                     preempt_worker_at_window={1: 1})
    t = dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", learning_rate=0.05,
                num_workers=2, batch_size=16, communication_window=2,
                num_epoch=2, backend="ps", elastic=True,
                ps_pipeline_depth=1, fault_plan=plan,
                preempt_drain_timeout=30.0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t.train(ds, shuffle=False)
    el = t.resilience_stats_["elastic"]
    assert el["joined"] == 1 and el["preempted"] == 1
    assert el["drain_timeouts"] == 0
    assert el["assigner"]["exactly_once"], el["assigner"]
    assert t.ps_stats_["fused_exchanges"] == t.ps_stats_["commits"]
    assert np.isfinite(final_loss(t))


# -- the EASGD drain satellite (PR 9 follow-up) ------------------------------


def test_easgd_clean_drain_commits_final_elastic_difference(monkeypatch):
    """A cleanly drained elastic-rule worker must commit its final
    elastic difference before deregistering — the center ends at
    ``c + α·(w − c)`` (pinned bitwise against the worker's stashed
    final state), instead of silently dropping everything the local
    variable held beyond the center."""
    import distkeras_tpu as dk
    from distkeras_tpu import workers as workers_mod
    from distkeras_tpu.resilience.faults import FaultPlan

    created = []
    orig_init = workers_mod.AsyncWorker.__init__

    def spy_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        created.append(self)

    monkeypatch.setattr(workers_mod.AsyncWorker, "__init__", spy_init)

    ds = blobs_dataset(n=512)
    plan = FaultPlan(seed=1, preempt_worker_at_window={0: 2})
    t = dk.AEASGD(model_spec(), loss="sparse_softmax_cross_entropy",
                  worker_optimizer="sgd", learning_rate=0.05, rho=0.5,
                  num_workers=1, batch_size=16, communication_window=2,
                  num_epoch=4, backend="ps", elastic=True,
                  fault_plan=plan, preempt_drain_timeout=30.0)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        weights = t.train(ds, shuffle=False)

    drained = [w for w in created if hasattr(w, "drained_center_")]
    assert len(drained) == 1, "the preempted worker ran the drain commit"
    w = drained[0]
    rule = t.allocate_merge_rule()
    diff = rule.worker_commit(w.final_params_, w.drained_center_)
    expected = rule.fold(w.drained_center_, diff, 1, 0)
    assert _tree_equal(weights, expected)
    # the drain commit is one extra fold past the per-window commits
    hist = [r for r in t.get_history() if "loss" in r]
    assert t.ps_stats_["commits"] == len(hist) + 1
    assert t.resilience_stats_["elastic"]["preempted"] == 1
