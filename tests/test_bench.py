"""bench.py plumbing tests: the measurement core runs on CPU and the analytic
FLOP models are sane (guards the driver-facing benchmark against bitrot)."""

import json
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench


def test_measure_runs_tiny_mlp_on_cpu():
    import jax
    import jax.numpy as jnp
    import optax

    from distkeras_tpu.datasets import higgs
    from distkeras_tpu.models import mlp
    from distkeras_tpu.parallel.merge_rules import ADAGMerge

    train, _ = higgs(n_train=512, n_test=16)
    sps = bench.measure(
        jax.devices("cpu")[0],
        mlp(input_shape=(28,), hidden=(16,), num_classes=2, dtype=jnp.float32),
        ADAGMerge(), optax.sgd(0.01), train, ["features", "label"],
        batch_size=32, window=2, epochs_timed=1,
    )[0]
    assert sps > 0 and np.isfinite(sps)


def test_measure_stacked_workers_on_one_device():
    import jax
    import jax.numpy as jnp
    import optax

    from distkeras_tpu.datasets import higgs
    from distkeras_tpu.models import mlp
    from distkeras_tpu.parallel.merge_rules import ADAGMerge

    train, _ = higgs(n_train=1024, n_test=16)
    sps = bench.measure(
        jax.devices("cpu")[0],
        mlp(input_shape=(28,), hidden=(16,), num_classes=2, dtype=jnp.float32),
        ADAGMerge(), optax.sgd(0.01), train, ["features", "label"],
        batch_size=32, window=2, num_workers=4, epochs_timed=1,
    )[0]
    assert sps > 0


def test_ps_microbench_smoke():
    """--ps-bench plumbing: a tiny in-process run produces positive rates
    and carries the contention counters (full-size runs are manual)."""
    out = bench.run_ps_microbench(n_params=16_384, workers=2, seconds=0.2,
                                  transports=("inprocess",))
    assert set(out) == {"ps_inprocess_raw", "ps_inprocess_int8"}
    for rec in out.values():
        assert rec["pulls_per_sec"] > 0
        assert rec["commits_per_sec"] > 0
        assert rec["mixed_rounds_per_sec"] > 0
        assert rec["center_lock_mean_hold_ns"] >= 0


def test_ps_shard_bench_contract():
    """--ps-bench's N-shard legs (ISSUE 8): every (transport, N) record
    present with positive aggregate rates, the per-shard byte split
    summing to the tree, and the host-ceiling field carried."""
    out = bench.run_ps_shard_bench(n_params=16_384, workers=2,
                                   seconds=0.2, shard_counts=(1, 2),
                                   transports=("socket",))
    assert set(out) == {"ps_shard_socket_n1", "ps_shard_socket_n2"}
    for name, rec in out.items():
        assert rec["pulls_per_sec"] > 0, name
        assert rec["commits_per_sec"] > 0, name
        assert rec["host_cores"] >= 1
        assert len(rec["shard_nbytes"]) == rec["num_shards"]
        assert rec["bytes_per_commit_per_shard"] == max(rec["shard_nbytes"])
    # sharding divides the per-shard fold cost — the structural claim
    assert (out["ps_shard_socket_n2"]["bytes_per_commit_per_shard"]
            < out["ps_shard_socket_n1"]["bytes_per_commit_per_shard"])


def test_ps_exchange_bench_contract():
    """--ps-bench's exchange leg (ISSUE 10 + 12): serial vs fused vs
    fused+pipelined records present with positive rates, the measured
    RTT-per-round oracle (2 for serial, 1 for fused — the wire-cost
    halving read off ps.stats(), not asserted), the host-ceiling
    honesty field, and the ISSUE 12 columns: an shm leg next to the
    socket leg, the shm-vs-socket ratio recorded on it, and the
    batched-fold lock-amortization fields on every leg. Rate ORDERING
    is asserted only for the counters-based claim; wall-clock speedups
    and cross-transport ratios are recorded, not asserted (CI hosts
    jitter)."""
    out = bench.run_ps_exchange_bench(n_params=16_384, workers=(2,),
                                      seconds=0.4,
                                      transports=("socket", "shm"),
                                      compute_ms=2.0)
    assert set(out) == {"ps_exchange_socket_w2", "ps_exchange_shm_w2"}
    for name, rec in out.items():
        for k in ("serial_rounds_per_sec", "fused_rounds_per_sec",
                  "pipelined_rounds_per_sec"):
            assert rec[k] > 0, (name, k)
        # the acceptance counter oracle: 1 wire RTT per fused round, 2
        # per serial round (pull-side counters settle exactly)
        assert 1.9 <= rec["serial_rtts_per_round"] <= 2.1, name
        assert 0.9 <= rec["fused_rtts_per_round"] <= 1.1, name
        assert rec["fused_exchanges"] > 0, name
        assert rec["host_cores"] >= 1, name
        assert rec["speedup_pipelined_vs_serial"] > 0, name
        # ISSUE 12: the batched-fold columns ride every leg
        assert rec["batched_folds"] >= 0, name
        assert rec["fused_lock_acquires_per_round"] > 0, name
    shm_rec = out["ps_exchange_shm_w2"]
    for leg in ("serial", "fused", "pipelined"):
        assert shm_rec[f"shm_vs_socket_{leg}"] > 0, leg


def test_ps_group_commit_sweep_contract():
    """--chaos-ps's flush-window sweep (ISSUE 7 + the ISSUE 12 shm leg):
    every leg present with positive rates, the exactly-once oracle
    asserted per leg, the durable legs carrying the WAL amortization
    counters, and the durable-vs-raw fraction computed against the
    no-WAL line — on the socket AND shm transports."""
    out = bench.run_ps_group_commit_sweep(n_params=16_384, workers=2,
                                          seconds=0.25,
                                          transports=("socket", "shm"))
    assert set(out) == {"ps_group_commit_socket", "ps_group_commit_shm"}
    for name, rec in out.items():
        assert set(rec["legs"]) == {"nowal", "w1", "w8", "w32", "time"}, name
        assert rec["host_cores"] >= 1 and rec["wal_fs"]
        for leg, r in rec["legs"].items():
            assert r["rounds_per_sec"] > 0, (name, leg)
            assert r["dedup_exact_once"], (name, leg)
            assert "invalid" not in r, (name, leg)
            if leg == "nowal":
                assert r["wal_records"] == 0
            else:
                assert r["wal_records"] > 0
                assert 0 < r["durable_fraction"]
                if leg != "time":  # a short run may not cross the deadline
                    assert r["wal_fsyncs"] >= 1
        assert rec["durable_fraction_w8"] == \
            rec["legs"]["w8"]["durable_fraction"]


def test_ps_elastic_bench_contract():
    """--chaos's elastic leg (ISSUE 9): the join + preempt sweep record
    carries the three phases with positive rates, the live join/drain
    pool counters, the ±1-worker tracking verdict with its host-ceiling
    honesty fields, and the exactly-once dedup oracle."""
    out = bench.run_ps_elastic_bench(n_params=16_384, workers=2,
                                     join_workers=1, seconds=0.9,
                                     pace_s=0.01)
    rec = out["ps_elastic_socket"]
    assert [p["name"] for p in rec["phases"]] == [
        "base", "joined", "drained"]
    assert [p["pool"] for p in rec["phases"]] == [2, 3, 2]
    for p in rec["phases"]:
        assert p["rounds_per_sec"] > 0, p
    assert rec["dedup_exact_once"]
    assert rec["pool_stats"]["joined_workers"] == 1
    assert rec["pool_stats"]["preempted_workers"] == 1
    assert rec["pool_stats"]["drain_timeouts"] == 0
    assert rec["pool_stats"]["pool_size"] == 2  # back to base after drain
    assert rec["host_cores"] >= 1
    assert isinstance(rec["tracking_within_one_worker"], bool)
    # a failed tracking verdict is only acceptable when host-ceiling-capped
    assert rec["tracking_within_one_worker"] or rec["host_ceiling_limited"]


def test_regress_metric_direction():
    """The comparator's direction map: throughput up, latency down,
    identity/shape keys skipped; the trajectory's `value` headline is a
    rate only when its record's unit says so."""
    assert bench.metric_direction("fused_rounds_per_sec") == "higher"
    assert bench.metric_direction("tokens_per_sec") == "higher"
    assert bench.metric_direction("throughput_rps") == "higher"
    assert bench.metric_direction("mfu") == "higher"
    assert bench.metric_direction("ms_per_step") == "lower"
    assert bench.metric_direction("p99_ms") == "lower"
    assert bench.metric_direction("tta_99_seconds") == "lower"
    assert bench.metric_direction("workers") is None
    assert bench.metric_direction("host_cores") is None
    assert bench.metric_direction(
        "value", {"unit": "samples/sec"}) == "higher"
    assert bench.metric_direction("value", {"unit": "loss"}) is None


def test_regress_comparator_flags_twenty_percent_slowdown():
    """The acceptance comparator case: a >= 20% drop against a tight
    trajectory is a regression; a within-noise drop is not; a noisy
    trajectory widens its own tolerance (measured spread, not an
    assumed constant)."""
    base = [{"config": "leg", "fused_rounds_per_sec": v}
            for v in (100.0, 101.0, 99.0, 100.5)]
    slow = [{"config": "leg", "fused_rounds_per_sec": 80.0}]
    r = bench.compare_to_trajectory(slow, base)
    assert r["verdict"] == "regression" and r["regressions"] == 1
    ok = bench.compare_to_trajectory(
        [{"config": "leg", "fused_rounds_per_sec": 97.0}], base)
    assert ok["verdict"] == "ok"
    # wide measured spread -> the same 20% drop is within tolerance
    noisy = [{"config": "leg", "fused_rounds_per_sec": v}
             for v in (100.0, 60.0, 140.0, 85.0, 115.0)]
    r2 = bench.compare_to_trajectory(slow, noisy)
    assert r2["checks"][0]["status"] == "ok"


def test_regress_comparator_direction_host_and_baseline_rules():
    # lower-better: a latency INCREASE regresses
    base = [{"config": "leg", "p99_ms": v} for v in (10.0, 10.5, 9.8)]
    r = bench.compare_to_trajectory([{"config": "leg", "p99_ms": 14.0}],
                                    base)
    assert r["verdict"] == "regression"
    r2 = bench.compare_to_trajectory([{"config": "leg", "p99_ms": 9.0}],
                                     base)
    assert r2["verdict"] == "ok"
    # host_cores-honest: samples from a different core count are not a
    # baseline — with all of them excluded the check is no_baseline
    alien = [{"config": "leg", "p99_ms": 5.0, "host_cores": 64}
             for _ in range(3)]
    r3 = bench.compare_to_trajectory(
        [{"config": "leg", "p99_ms": 14.0}], alien, host_cores=1)
    (chk,) = r3["checks"]
    assert chk["status"] == "no_baseline" and chk["host_skipped"] == 3
    assert r3["verdict"] == "ok"
    # fewer than min_samples baselines: the trajectory starts here
    r4 = bench.compare_to_trajectory(
        [{"config": "leg", "p99_ms": 14.0}],
        [{"config": "leg", "p99_ms": 10.0}])
    assert r4["checks"][0]["status"] == "no_baseline"


def test_regress_load_trajectory_parses_parsed_and_tail(tmp_path):
    doc = {
        "n": 1, "cmd": "python bench.py", "rc": 0,
        "parsed": {"config": "a", "tokens_per_sec": 100.0},
        "tail": "\n".join([
            "noise line",
            '{"config": "b", "ms_per_step": 5.0}',
            '{"config": "bad", "ms_per_step": 9.0, "invalid": true}',
            '{"config": "a", "tokens_per_sec": 100.0}',  # dup of parsed
            "{not json}",
        ]),
    }
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(doc))
    files, recs = bench.load_trajectory("BENCH_*.json", str(tmp_path))
    assert len(files) == 1
    # dup deduped, invalid dropped, non-JSON ignored
    assert sorted(r["config"] for r in recs) == ["a", "b"]
    assert all(r["_file"] == "BENCH_r01.json" for r in recs)


def test_regress_bench_smoke_clean_and_synthetic_slowdown(tmp_path):
    """--regress end to end at toy scale: an unmodified measurement
    passes against its own clean repeats; the synthetic-slowdown seam
    (a REAL injected sleep) is flagged. Empty glob -> the clean repeats
    are the whole baseline, exactly the trajectory-seeding path."""
    # rel_slack loosened to 35% for the in-suite smoke (ISSUE 14
    # jitter-hardening): the suite's own load jitters this box well
    # past the guard's 12% default (which CI runs with the step alone)
    # — the known ±15% suite-load envelope lands on top of the clean
    # repeats' own spread (the watched-fused-dip class of flake), so
    # the slack budgets both. The injected slowdown therefore grows to
    # 2.0 ms/round (a measured ~−50% at this round size — a 1.0
    # injection came back −34% in-suite, INSIDE the widened slack).
    rec = bench.run_regress_bench(
        repeats=2, seconds=0.3, n_params=16_384, slowdown=0.0,
        glob_pat="NO_SUCH_BENCH_*.json", root=str(tmp_path),
        rel_slack=0.35,
    )
    assert rec["verdict"] == "ok", rec["checks"]
    assert rec["trajectory_files"] == 0
    keys = {c["key"] for c in rec["checks"]}
    assert "fused_rounds_per_sec" in keys
    slow = bench.run_regress_bench(
        repeats=2, seconds=0.3, n_params=16_384, slowdown=2.0,
        glob_pat="NO_SUCH_BENCH_*.json", root=str(tmp_path),
        rel_slack=0.35,
    )
    assert slow["verdict"] == "regression", slow["checks"]
    flagged = {c["key"] for c in slow["checks"]
               if c["status"] == "regression"}
    # the sleep rides inside the measured round on the serial/fused
    # legs (the pipelined leg may hide part of it in its overlap) —
    # at least one rounds/s leg must be flagged
    assert any(k.endswith("_rounds_per_sec") for k in flagged), flagged


def test_analytic_flop_models():
    # hand-checked reference points (training = 3× forward)
    assert bench.mlp_flops((784, 500, 300, 10)) == 3 * 2 * (
        784 * 500 + 500 * 300 + 300 * 10
    )
    # LeNet ≈ 69 MFLOP/sample trained (the round-1 judge's estimate)
    assert 60e6 < bench.lenet_flops() < 80e6
    # VGG-small is ~13× LeNet
    assert 10 < bench.vgg_small_flops() / bench.lenet_flops() < 16
    # LSTM: 200 steps × 8·H·(E+H)
    assert bench.lstm_flops() == 3 * (200 * 8 * 128 * 256 + 2 * 128 * 2)


def test_transformer_flop_model():
    d, depth, L = 512, 8, 2048
    assert bench.transformer_flops_per_token(d, depth, L) == \
        3 * depth * (24 * d * d + 4 * L * d)


def test_peak_flops_by_device_kind():
    class Fake:
        platform = "tpu"
        def __init__(self, kind):
            self.device_kind = kind

    assert bench.peak_flops(Fake("TPU v5 lite")) == 197e12
    assert bench.peak_flops(Fake("TPU v5p")) == 459e12
    assert bench.peak_flops(Fake("TPU v6e")) == 918e12
    assert bench.peak_flops(Fake("TPU v4")) == 275e12
    assert bench.peak_flops(Fake("TPU vNext")) == 197e12  # unknown default

    class Cpu:
        platform = "cpu"
        device_kind = "cpu"

    assert bench.peak_flops(Cpu()) is None


def test_serving_bench_smoke():
    """--serve plumbing: a tiny run produces the stdout-JSON record
    contract the BENCH_* trajectory consumes — throughput, latency
    percentiles, the sequential/static-batch reference points, and the
    engine stats (full-size runs are manual / --full)."""
    out = bench.run_serving_bench(
        vocab=64, maxlen=32, dim=32, heads=2, depth=1, prompt_len=4,
        max_new=4, max_batch=2, n_baseline=2, rates=(8.0,), seconds=0.3,
        legs=("paged",),
    )
    assert set(out) == {"serve_paged"}
    rec = out["serve_paged"]
    for key in ("sequential_rps", "static_batch_rps", "host_ceiling_x",
                "throughput_rps", "p50_ms", "p99_ms",
                "speedup_vs_sequential", "bound_fraction",
                "mean_batch_occupancy", "blocks_high_water",
                "target_3x_met"):
        assert key in rec, key
    assert rec["sequential_rps"] > 0
    assert rec["throughput_rps"] > 0
    assert rec["p99_ms"] >= rec["p50_ms"]
    assert rec["rates"] and all("offered_rps" in r for r in rec["rates"])
    # every accepted request completed (none stranded by the drain)
    assert rec["completed"] > 0


def test_serve_prefix_bench_smoke():
    """--serve-legs prefix plumbing (ISSUE 17): the shared-system-prompt
    leg's stdout-JSON record contract — prefill ms at ~0% vs high hit
    rate off the SAME engine, keyed so the --regress trajectory judges
    cold/warm prefill as lower-better metrics."""
    spec, params = bench._serve_lm(64, 64, 32, 2, 1, "f32")
    rec = bench.run_serve_prefix_bench(
        spec, params, 64, max_new=4, max_batch=2, block_size=8,
        sys_len=24, tail_len=8, n_requests=4, prefill_chunk=8, seed=0)
    for key in ("cold_prefill_ms", "warm_prefill_ms", "prefill_speedup",
                "cold_hit_rate", "warm_hit_rate", "prefix_cached_blocks",
                "cow_copies", "host_cores"):
        assert key in rec, key
    assert rec["config"] == "serve_prefix"
    # the acceptance shape: hit rate rises, prefill cost falls with it
    assert rec["cold_hit_rate"] == 0.0
    assert rec["warm_hit_rate"] >= 0.5
    assert rec["cold"]["completed"] == rec["warm"]["completed"] == 4
    # the trajectory contract sees these as performance metrics
    assert bench.metric_direction("cold_prefill_ms") == "lower"
    assert bench.metric_direction("warm_prefill_ms") == "lower"
    assert bench.metric_direction("host_cores") is None


def test_serve_tenants_bench_smoke():
    """--serve-legs tenants plumbing (ISSUE 17): the mixed-tenant SLO
    record contract — realtime p99 under FIFO vs slo admission on a
    block-starved engine, with the preemption count best-effort
    absorbed."""
    spec, params = bench._serve_lm(64, 160, 32, 2, 1, "f32")
    rec = bench.run_serve_tenants_bench(
        spec, params, 64, max_batch=4, block_size=16, n_batch=3,
        n_rt=2, rt_gap_s=0.05, seed=0)
    for key in ("fifo_rt_p99_ms", "slo_rt_p99_ms", "fifo_be_p99_ms",
                "slo_be_p99_ms", "rt_p99_gain_x", "preemptions",
                "host_cores"):
        assert key in rec, key
    assert rec["config"] == "serve_tenants"
    # nothing stranded, nothing leaked, on either engine
    for leg in ("fifo", "slo"):
        assert rec[leg]["rt_completed"] == 2
        assert rec[leg]["be_completed"] == 3
        assert rec[leg]["blocks_in_use_after"] == 0
    assert bench.metric_direction("slo_rt_p99_ms") == "lower"
    assert bench.metric_direction("preemptions") is None
