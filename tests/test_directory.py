"""Membership directory & routing (ISSUE 15).

Pins:

- **WAL replay bit-identity**: a crashed directory's ``(snapshot, wal)``
  replays to exactly the live server's state, and ``wal verify`` walks a
  directory root (flagging it as one).
- **lease expiry under a seeded stalled heartbeat** (injected clock — no
  wall-time races): an unrenewed entry ages out, a renewed one survives,
  and the expiry is itself a durable record.
- **registration races**: two promotions in either arrival order resolve
  to the higher fence epoch.
- **chain replication + promotion**: primary → standby → standby applies
  the same records via the shared apply function; promotion stamps the
  bumped epoch and keeps streaming down-chain.
- **publish-then-fence**: the failover's epoch bump is atomic with the
  repoint and the directory publication lands BEFORE the old primary's
  fence — and after a failover against a live (zombie) old primary, an
  old-epoch commit to it is fenced while the new primary serves the new
  epoch.
- **discovery**: a client built from a directory lookup alone (no
  endpoint constructor args) trains against a sharded fleet; a
  plan-digest mismatch fails fast.
- **the chaos acceptance**: kill one PS shard AND the directory primary
  mid-run (elastic, with a mid-run joiner minted from the directory) —
  completes, exactly-once per shard, WAL-replay center bit-identical.
- **the router**: ≥8 concurrent clients over 2 GenerationServers show
  prefix-hash affinity and survive one replica killed mid-stream with
  every surviving stream completing.

Timing assertions ride injected clocks wherever possible; the few
wall-clock waits carry the tier-1 suite's ±15% load-jitter margins
(bounds at 3× the nominal interval).
"""

import os
import socket as _socket
import threading
import time
import warnings

import numpy as np
import pytest

from distkeras_tpu.directory import (
    DirectoryClient,
    DirectoryEndpoint,
    DirectoryServer,
    RoutedGenerationClient,
    StandbyDirectoryServer,
    build_ps_client,
    parse_seeds,
    recover_directory_state,
)
from distkeras_tpu.networking import (
    FencedEpochError,
    ShardMapMismatchError,
)
from distkeras_tpu.resilience import wal as walmod
from distkeras_tpu.resilience.faults import FaultPlan
from distkeras_tpu.resilience.retry import (
    PSEndpoint,
    ResilientPSClient,
    RetryPolicy,
)
from tests.test_trainers import blobs_dataset, final_loss, model_spec


class FakeClock:
    def __init__(self, t=100.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


def _start(srv):
    srv.initialize()
    srv.start()
    return srv


# -- seeds & basic map -------------------------------------------------------


def test_parse_seeds_shapes():
    assert parse_seeds("h:9") == [("h", 9)]
    assert parse_seeds([("a", 1), "b:2"]) == [("a", 1), ("b", 2)]
    assert parse_seeds(("a", 1)) == [("a", 1)]
    with pytest.raises(ValueError, match="host:port"):
        parse_seeds(["nope"])
    with pytest.raises(ValueError, match="at least one"):
        parse_seeds([])


def test_publish_lookup_withdraw_roundtrip():
    srv = _start(DirectoryServer(default_ttl=None))
    try:
        c = DirectoryClient([(srv.host, srv.port)])
        assert c.publish("ps", "shard-00", "10.0.0.1", 7000,
                         meta={"num_shards": 1})["ok"]
        es = c.lookup("ps")
        assert [(e["key"], e["host"], e["port"]) for e in es] \
            == [("shard-00", "10.0.0.1", 7000)]
        assert c.lookup("serve") == []
        assert c.withdraw("ps", "shard-00")["ok"]
        assert c.lookup("ps") == []
        # withdrawing an absent entry is idempotent
        assert c.withdraw("ps", "shard-00")["ok"]
        c.close()
    finally:
        srv.stop()


def test_lease_expiry_under_stalled_heartbeat():
    """The seeded stalled-heartbeat scenario on an injected clock: two
    entries, one renews, one stalls — only the stalled one expires, the
    expiry is a durable dir_expire record, and a lookup never serves a
    lapsed lease."""
    clock = FakeClock()
    srv = DirectoryServer(default_ttl=2.0, clock=clock)
    srv.publish("ps", "live", "h", 1)
    srv.publish("ps", "stalled", "h", 2)
    for _ in range(4):
        clock.advance(1.0)          # stalled worker's heartbeats stop
        srv.renew("ps", "live")     # the live one keeps renewing
    got = {e["key"] for e in srv.lookup("ps")}
    assert got == {"live"}
    assert srv.expired_entries == 1
    assert srv.stats()["entries"] == 1
    # the expiry changed the replayed map, not just the runtime view
    state = srv.state.snapshot()
    assert ("ps", "stalled") not in state["entries"]
    # a re-registration (the promoted owner coming back) re-admits
    srv.publish("ps", "stalled", "h2", 3, epoch=1)
    assert {e["key"] for e in srv.lookup("ps")} == {"live", "stalled"}


def test_registration_race_higher_fence_epoch_wins_both_orders():
    srv = _start(DirectoryServer(default_ttl=None))
    try:
        c = DirectoryClient([(srv.host, srv.port)])
        # order A: high then low — the stale promotion is REJECTED
        assert c.publish("ps", "shard-00", "new", 2, epoch=5)["ok"]
        r = c.publish("ps", "shard-00", "old", 1, epoch=3)
        assert not r["ok"] and r["error"] == "stale_epoch" \
            and r["epoch"] == 5
        assert c.lookup("ps", "shard-00")[0]["host"] == "new"
        # order B: low then high — the higher epoch replaces
        assert c.publish("ps", "shard-01", "old", 1, epoch=3)["ok"]
        assert c.publish("ps", "shard-01", "new", 2, epoch=5)["ok"]
        assert c.lookup("ps", "shard-01")[0]["host"] == "new"
        # stale withdraw cannot erase the promoted entry either
        assert not c.withdraw("ps", "shard-01", epoch=3)["ok"]
        assert c.lookup("ps", "shard-01")[0]["host"] == "new"
        assert srv.stale_rejects == 2
        c.close()
    finally:
        srv.stop()


# -- durability --------------------------------------------------------------


def test_directory_wal_replay_bit_identity(tmp_path):
    """Crash (no tidy close) after a mixed event history; the recovered
    state — across a mid-history snapshot truncation — equals the live
    state exactly, and the verify tool reports the root healthy AND
    flags it as a directory log."""
    d = str(tmp_path)
    srv = _start(DirectoryServer(wal_dir=d, default_ttl=None,
                                 snapshot_every=3))
    srv.publish("ps", "shard-00", "h", 1)
    srv.publish("ps", "shard-01", "h", 2)
    srv.publish("serve", "r1", "h", 3)
    srv.publish("ps", "shard-00", "h2", 4, epoch=1)   # failover repoint
    srv.withdraw("serve", "r1")
    srv.fence(2)
    live = srv.state.snapshot()
    srv._crash()

    rec = recover_directory_state(d)
    assert rec is not None and rec.snapshot() == live
    report = walmod.verify_tree(d)
    assert report["ok"], report
    assert report["directory"] is True
    assert report["record_totals"].get("dir_fence") == 1
    # restart-in-place adopts the same state and keeps serving
    srv2 = _start(DirectoryServer(wal_dir=d, default_ttl=None))
    try:
        assert srv2.recovered_ and srv2.state.snapshot() == live
        c = DirectoryClient([(srv2.host, srv2.port)])
        assert {e["key"] for e in c.lookup("ps")} \
            == {"shard-00", "shard-01"}
        c.close()
    finally:
        srv2.stop()


def test_wal_verify_walks_shared_root_with_directory(tmp_path):
    """A training root holding per-shard commit logs AND the directory's
    log under ``directory/`` verifies as ONE aggregate report that
    counts the directory dirs — an out-of-date or torn directory log is
    operator-visible, not silent."""
    from distkeras_tpu.parallel.merge_rules import DownpourMerge
    from distkeras_tpu.parameter_servers import ParameterServer

    root = str(tmp_path)
    ps = ParameterServer({"w": np.zeros(8, np.float32)}, DownpourMerge(),
                         1, wal_dir=os.path.join(root, "shard-00"),
                         wal_group_window=1)
    ps.pull(0)
    ps.commit(0, {"w": np.ones(8, np.float32)}, seq=1)
    ps.stop()
    dsrv = DirectoryServer(wal_dir=os.path.join(root, "directory"),
                           default_ttl=None)
    dsrv.publish("ps", "shard-00", "h", 1)
    dsrv.stop()
    rep = walmod.verify_tree(root)
    assert rep["ok"] and rep.get("sharded")
    assert rep["num_directory_dirs"] == 1
    by_dir = {r["dir"]: r for r in rep["dirs"]}
    assert by_dir["directory"]["directory"] is True
    assert by_dir["shard-00"]["directory"] is False
    # a torn directory tail on a NON-live segment must fail the report
    ddir = os.path.join(root, "directory")
    seg = sorted(n for n in os.listdir(ddir) if n.startswith("wal-"))[0]
    with open(os.path.join(ddir, seg), "r+b") as f:
        f.seek(0, 2)
        size = f.tell()
        f.truncate(max(size - 3, 1))
    with open(os.path.join(ddir, "wal-999999999999.log"), "wb") as f:
        f.write(b"")  # a later (live) segment makes the torn one non-live
    assert not walmod.verify_tree(root)["ok"]


def test_ttl_only_republish_is_durable(tmp_path):
    """A re-publish changing ONLY the lease ttl must be a logged (and
    streamed) record: the recovered/promoted directory re-arms leases
    from the stored ttl, so a skipped log entry would immortalize (or
    erase) the entry after a failover."""
    d = str(tmp_path)
    srv = DirectoryServer(wal_dir=d, default_ttl=None)
    srv.publish("ps", "shard-00", "h", 1, ttl=None)
    srv.publish("ps", "shard-00", "h", 1, ttl=2.0)   # lease-mode flip only
    live = srv.state.snapshot()
    assert live["entries"][("ps", "shard-00")]["ttl"] == 2.0
    srv._crash()
    rec = recover_directory_state(d)
    assert rec.snapshot() == live


def test_directory_restart_in_place_keeps_seed_address(tmp_path):
    """directory_standby=False + WAL: the supervisor's restart-in-place
    must rebind the ORIGINAL primary port — the seed list is every
    client's only bootstrap, so a replacement on a fresh ephemeral port
    would be unreachable by construction."""
    from distkeras_tpu.directory import HostedDirectory

    hosted = HostedDirectory(wal_dir=str(tmp_path), standby=False,
                             failover_timeout=0.3)
    hosted.start()
    try:
        seeds = hosted.seeds
        c = DirectoryClient(seeds)
        c.publish("ps", "shard-00", "h", 7, ttl=None)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")   # the failover notice
            hosted.primary._crash()
            # the ONLY addresses we hold are the original seeds; the
            # restarted (WAL-recovered) primary must answer on them
            deadline = time.monotonic() + 15.0
            entries = []
            while time.monotonic() < deadline:
                try:
                    entries = c.lookup("ps", "shard-00")
                    if entries:
                        break
                except ConnectionError:
                    pass
                time.sleep(0.1)
        assert entries and entries[0]["port"] == 7
        assert hosted.supervisor.failovers == 1
        assert hosted.active.port == seeds[0][1]
        c.close()
    finally:
        hosted.stop()


# -- replication & promotion -------------------------------------------------


def test_chain_replication_apply_and_forward_and_promotion():
    """primary → s1 → s2: every record applies on both links via the
    shared apply function; promoting s1 stamps the bumped epoch, re-arms
    leases, and KEEPS forwarding its own writes to s2 (the chain
    survives its head's promotion)."""
    srv = _start(DirectoryServer(default_ttl=None))
    s1 = _start(StandbyDirectoryServer(default_ttl=None))
    s2 = _start(StandbyDirectoryServer(default_ttl=None))
    try:
        s1.attach_standby(s2.host, s2.port)   # tail first
        srv.attach_standby(s1.host, s1.port)
        srv.publish("ps", "shard-00", "h", 1)
        srv.publish("serve", "r", "h", 2)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and (len(s2.state.entries) < 2 or len(s1.state.entries) < 2):
            time.sleep(0.01)
        assert s1.state.snapshot() == srv.state.snapshot()
        assert s2.state.snapshot() == srv.state.snapshot()
        # promote the head of the chain
        srv._crash()
        s1.promote(epoch=3)
        assert s1.fence_epoch == 3 and not s1.is_standby and s1.promoted_
        # the promoted primary's own writes keep streaming to s2
        s1.publish("ps", "shard-00", "h9", 9, epoch=3)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and s2.state.entries.get(("ps", "shard-00"),
                                         {}).get("port") != 9:
            time.sleep(0.01)
        assert s2.state.entries[("ps", "shard-00")]["port"] == 9
        assert s2.state.fence_epoch == 3   # the fence rode the chain too
        # client over the seed list lands on the promoted primary
        c = DirectoryClient([(srv.host, srv.port), (s1.host, s1.port)])
        assert c.lookup("ps", "shard-00")[0]["port"] == 9
        c.close()
    finally:
        for s in (srv, s1, s2):
            s.stop()


def test_standby_wal_rebased_on_stream_adoption(tmp_path):
    """A durable standby whose own WAL holds an OLDER history adopts a
    newer primary's base: its log is re-based (rotate + snapshot at the
    adopted version) so streamed records append gap-free and a later
    recovery replays cleanly — the version-gap hazard pinned."""
    stb_dir = str(tmp_path)
    # seed the standby's wal dir with an old history at version 1
    old = DirectoryServer(wal_dir=stb_dir, default_ttl=None)
    old.publish("ps", "stale", "h", 1)
    old.stop()
    primary = _start(DirectoryServer(default_ttl=None))
    for i in range(3):
        primary.publish("ps", f"shard-{i:02d}", "h", 10 + i)
    stb = _start(StandbyDirectoryServer(wal_dir=stb_dir,
                                        default_ttl=None))
    assert stb.recovered_ and stb.state.version == 1
    try:
        primary.attach_standby(stb.host, stb.port)   # adopts version 3
        primary.publish("ps", "shard-03", "h", 13)   # streams record 4
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and stb.state.version < 4:
            time.sleep(0.01)
        assert stb.state.snapshot() == primary.state.snapshot()
        stb._crash()
        rec = recover_directory_state(stb_dir)   # must not gap-error
        assert rec is not None
        assert rec.snapshot() == primary.state.snapshot()
    finally:
        primary.stop()
        stb.stop()


def test_client_prefers_highest_epoch_never_a_zombie():
    """Two serving directories (a promoted replica at epoch 2 and a
    zombie old primary at epoch 0): the seed probe picks the higher
    fence epoch regardless of seed order."""
    zombie = _start(DirectoryServer(default_ttl=None))
    zombie.publish("ps", "shard-00", "stale", 1)
    promoted = _start(DirectoryServer(default_ttl=None, fence_epoch=2))
    promoted.publish("ps", "shard-00", "fresh", 2, epoch=2)
    try:
        for seeds in ([(zombie.host, zombie.port),
                       (promoted.host, promoted.port)],
                      [(promoted.host, promoted.port),
                       (zombie.host, zombie.port)]):
            c = DirectoryClient(seeds)
            assert c.lookup("ps", "shard-00")[0]["host"] == "fresh"
            c.close()
    finally:
        zombie.stop()
        promoted.stop()


# -- publish-then-fence (the pinned ordering fix) ----------------------------


def test_failover_publish_then_fence_ordering():
    """The supervisor's failover: (promote) → (resolver + directory
    publish, atomically carrying the bumped epoch) → (fence). At fence
    time the resolver must already name the new primary at the new
    epoch and the directory entry must already be written — no consumer
    can observe the endpoint without the epoch or vice versa."""
    from distkeras_tpu.resilience.recovery import PSFailoverSupervisor

    events = []

    class FakeStandby:
        host, port = "newhost", 4242
        promoted_ = False
        crashed_ = False
        _running = True

        def promote(self, epoch):
            events.append(("promote", epoch))
            self.promoted_ = True

    resolver = PSEndpoint("oldhost", 1111, epoch=0)
    published = []

    def publish(host, port, epoch):
        # the resolver was repointed BEFORE (or atomically with) the
        # directory publication — never after
        assert resolver.resolve() == (host, port, epoch)
        published.append((host, port, epoch))
        events.append(("publish", epoch))

    sup = PSFailoverSupervisor(resolver, primary=object(),
                               standby=FakeStandby(), publish=publish)

    def fence(host, port, epoch):
        events.append(("fence", epoch))
        # publish-then-fence: by fence time the system of record already
        # names the new primary at the new epoch
        assert resolver.resolve() == ("newhost", 4242, 1)
        assert published == [("newhost", 4242, 1)]
        assert (host, port) == ("oldhost", 1111)
        return True

    sup._try_fence = fence
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")   # the failover notice itself
        sup._failover_impl()
    assert [e[0] for e in events] == ["promote", "publish", "fence"]
    assert sup.failover_log[0]["fence_confirmed"] is True
    assert sup.failover_log[0]["published"] is True
    assert sup.publishes == 1


def test_zombie_primary_fenced_after_promotion_published():
    """Against a LIVE (stalled, not dead) old primary: after the
    failover, a slow worker's old-epoch commit to the zombie is fenced
    while the promoted primary serves the new epoch — the interleaving
    the publish-then-fence ordering (plus the fence retry) closes."""
    from distkeras_tpu.parallel.merge_rules import DownpourMerge
    from distkeras_tpu.parameter_servers import (
        ParameterServerClient,
        SocketParameterServer,
        StandbySocketParameterServer,
    )
    from distkeras_tpu.resilience.recovery import PSFailoverSupervisor

    tree = {"w": np.zeros(16, np.float32)}
    old = SocketParameterServer(dict(tree), DownpourMerge(), 2)
    old.initialize()
    old.start()
    stb = StandbySocketParameterServer(dict(tree), DownpourMerge(), 2)
    stb.initialize()
    stb.start()
    old.attach_standby("127.0.0.1", stb.port)
    resolver = PSEndpoint("127.0.0.1", old.port, epoch=0)
    sup = PSFailoverSupervisor(resolver, old, standby=stb)
    try:
        # the supervisor believes the primary dead (stalled pings); the
        # process itself is alive — the zombie scenario
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            sup._failover_impl()
        host, port, epoch = resolver.resolve()
        assert (host, port, epoch) == ("127.0.0.1", stb.port, 1)
        # fast worker commits to the NEW primary at the new epoch
        fast = ParameterServerClient("127.0.0.1", stb.port, 0, epoch=1)
        fast.pull()
        fast.commit(0, {"w": np.ones(16, np.float32)}, seq=1)
        # slow worker still wired to the OLD primary at the old epoch:
        # its commit is FENCED, not folded into the superseded history
        slow = ParameterServerClient("127.0.0.1", old.port, 1, epoch=0)
        with pytest.raises(FencedEpochError):
            slow.commit(1, {"w": np.ones(16, np.float32)}, seq=1)
        assert old.num_updates == 0 and stb.num_updates == 1
        assert sup.failover_log[0]["fence_confirmed"] is True
        fast.close()
        slow.close()
    finally:
        sup.stop()
        old.stop()
        stb.stop()


# -- directory-backed resolution ---------------------------------------------


def test_resilient_client_re_resolves_through_directory():
    """A ResilientPSClient whose resolver is a DirectoryEndpoint: the
    primary dies, a replacement registers under a bumped epoch, and the
    client's next op reconnects through a directory refresh — no
    hand-wired repoint anywhere."""
    from distkeras_tpu.parallel.merge_rules import DownpourMerge
    from distkeras_tpu.parameter_servers import (
        ParameterServerClient,
        SocketParameterServer,
    )

    tree = {"w": np.zeros(16, np.float32)}
    dsrv = _start(DirectoryServer(default_ttl=None))
    a = SocketParameterServer(dict(tree), DownpourMerge(), 1)
    a.initialize()
    a.start()
    dc = DirectoryClient([(dsrv.host, dsrv.port)])
    dc.publish("ps", "shard-00", "127.0.0.1", a.port, epoch=0)
    resolver = DirectoryEndpoint(dc, "ps", "shard-00")

    def mk():
        host, port, epoch = resolver.resolve()
        return ParameterServerClient(host, port, 0, epoch=epoch)

    client = ResilientPSClient(
        mk, 0, policy=RetryPolicy(max_attempts=60, base_delay=0.01,
                                  max_delay=0.1, deadline=30.0),
        resolver=resolver,
    )
    b = None
    try:
        client.pull()
        client.commit(0, {"w": np.ones(16, np.float32)})
        # primary dies; the replacement registers at epoch 1
        a._crash()
        b = SocketParameterServer(dict(tree), DownpourMerge(), 1,
                                  fence_epoch=1)
        b.initialize()
        b.start()
        dc.publish("ps", "shard-00", "127.0.0.1", b.port, epoch=1)
        client.pull()                      # reconnect → refresh → B
        client.commit(0, {"w": np.ones(16, np.float32)})
        assert b.num_updates == 1
        assert resolver.refreshes >= 1
        assert resolver.resolve() == ("127.0.0.1", b.port, 1)
    finally:
        client.close()
        dc.close()
        if b is not None:
            b.stop()
        a.stop()
        dsrv.stop()


def test_build_ps_client_from_directory_alone():
    """The PR 9 follow-up, by construction: a 2-shard fleet registered
    in the directory; a worker client is minted from the seeds + the
    local template ONLY (zero endpoint constructor args), passes the
    shard-map handshake, and trains exactly-once — while a wrong ring
    digest fails fast instead of mis-folding."""
    from distkeras_tpu.parallel.merge_rules import DownpourMerge
    from distkeras_tpu.sharding import ShardedPSGroup
    from distkeras_tpu.utils import tree_to_numpy

    rng = np.random.default_rng(0)
    tree = {"emb": rng.normal(size=(64,)).astype(np.float32),
            "w": rng.normal(size=(24,)).astype(np.float32),
            "b": rng.normal(size=(8,)).astype(np.float32)}
    group = ShardedPSGroup(tree, DownpourMerge(), 1, num_shards=2,
                           transport="socket")
    group.initialize()
    group.start()
    dsrv = _start(DirectoryServer(default_ttl=None))
    try:
        dc = DirectoryClient([(dsrv.host, dsrv.port)])
        meta = {"num_shards": 2, "ring": group.plan.digest,
                "vnodes": group.plan.ring.vnodes,
                "bound": group.plan.bound}
        for sid, srv in enumerate(group.servers):
            dc.publish("ps", f"shard-{sid:02d}", srv.host, srv.port,
                       epoch=0, meta=meta)
        client = build_ps_client([(dsrv.host, dsrv.port)],
                                 tree_to_numpy(tree), worker_id=0)
        base = client.pull()
        delta = {k: np.full_like(v, 0.5) for k, v in base.items()}
        client.commit(0, delta)
        got = client.pull()
        for k in tree:
            np.testing.assert_allclose(got[k], base[k] + 0.5)
        s = group.stats()
        assert s["num_updates"] == s["num_updates_max"] == 1
        client.close()
        # a fleet registered under a DIFFERENT plan digest fails fast
        dc.publish("ps", "shard-00", group.servers[0].host,
                   group.servers[0].port, epoch=1,
                   meta={**meta, "ring": "0" * 40})
        with pytest.raises(ShardMapMismatchError, match="different plan"):
            build_ps_client([(dsrv.host, dsrv.port)],
                            tree_to_numpy(tree), worker_id=1)
        dc.close()
    finally:
        dsrv.stop()
        group.stop()


def test_directory_partition_window_is_retried_through():
    """A deterministic directory partition (op-count window) tears
    lookups mid-flight; the client's retry/backoff rides it out and the
    drops are accounted."""
    plan = FaultPlan(seed=0, directory_partition_after=2,
                     directory_partition_ops=3)
    srv = _start(DirectoryServer(default_ttl=None, fault_plan=plan))
    try:
        c = DirectoryClient([(srv.host, srv.port)])
        c.publish("ps", "shard-00", "h", 1)          # op 1
        c.publish("ps", "shard-01", "h", 2)          # op 2
        for _ in range(4):                           # ops 3.. partitioned
            assert len(c.lookup("ps")) == 2
        assert plan.stats()["directory_drops"] == 3
        c.close()
    finally:
        srv.stop()


# -- trainer integration -----------------------------------------------------


def test_trainer_directory_run_and_stats():
    """directory=True end to end on the socket transport: the run
    trains through directory-minted clients, the registrations and the
    final membership land in resilience_stats_, and health_snapshot
    grows the directory section."""
    import distkeras_tpu as dk
    from distkeras_tpu.observability.metrics import health_snapshot

    ds = blobs_dataset(n=256)
    t = dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", learning_rate=0.1, num_workers=1,
                batch_size=32, communication_window=2, num_epoch=1,
                backend="ps", ps_transport="socket", directory=True,
                ps_num_shards=2)
    t.train(ds, shuffle=False)
    dstats = t.directory_stats_
    assert [tuple(k) for k in dstats["registered"]] \
        == [("ps", "shard-00"), ("ps", "shard-01")]
    keys = {e["key"] for e in dstats["membership"]["entries"]}
    assert keys == {"shard-00", "shard-01"}
    assert dstats["primary"]["lookups"] >= 1   # clients were minted here
    snap = health_snapshot(ps_stats=t.ps_stats_,
                           directory=dstats["membership"])
    assert {e["key"] for e in snap["directory"]["entries"]} == keys
    import json

    json.dumps(snap)          # the health artifact must stay JSON-clean
    json.dumps(t.resilience_stats_)


def test_trainer_validates_directory_knobs():
    import distkeras_tpu as dk

    kw = dict(loss="sparse_softmax_cross_entropy", worker_optimizer="sgd",
              num_workers=1, backend="ps")
    with pytest.raises(ValueError, match="socket"):
        dk.ADAG(model_spec(), directory=True, **kw)
    with pytest.raises(ValueError, match="exactly one"):
        dk.ADAG(model_spec(), ps_transport="socket", directory=True,
                ps_directory="h:1", **kw)
    with pytest.raises(ValueError, match="ps_host"):
        dk.ADAG(model_spec(), ps_transport="socket", directory=True,
                ps_host="10.0.0.1", **kw)
    with pytest.raises(ValueError, match="owner"):
        dk.ADAG(model_spec(), ps_transport="socket", ps_directory="h:1",
                ps_num_shards=2, **kw)
    with pytest.raises(ValueError, match="backend='ps'"):
        dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                worker_optimizer="sgd", num_workers=1, directory=True)
    # directory chaos without a directory would silently test nothing
    with pytest.raises(ValueError, match="directory"):
        dk.ADAG(model_spec(), ps_transport="socket",
                fault_plan=FaultPlan(kill_directory_after_ops=5), **kw)


def test_trainer_ps_directory_discovers_external_fleet():
    """ps_directory= : the worker process knows ONLY the directory
    seeds; the PS owner's fleet (here: a group this test hosts) is
    discovered, trained against, and the final center pulled — the
    ps_host story with the wiring looked up instead of hand-passed."""
    import distkeras_tpu as dk
    from distkeras_tpu.parameter_servers import SocketParameterServer

    spec = model_spec()
    t_probe = dk.ADAG(spec, loss="sparse_softmax_cross_entropy",
                      worker_optimizer="sgd", num_workers=2,
                      backend="ps")
    params, _ = t_probe.spec.init_np(t_probe.seed)
    rule = t_probe.allocate_merge_rule()
    ps = SocketParameterServer(params, rule, 2)
    ps.initialize()
    ps.start()
    dsrv = _start(DirectoryServer(default_ttl=None))
    try:
        dc = DirectoryClient([(dsrv.host, dsrv.port)])
        dc.publish("ps", "shard-00", "127.0.0.1", ps.port, epoch=0,
                   meta={"num_shards": 1})
        dc.close()
        ds = blobs_dataset(n=256)
        t = dk.ADAG(model_spec(), loss="sparse_softmax_cross_entropy",
                    worker_optimizer="sgd", learning_rate=0.1,
                    num_workers=2, batch_size=32,
                    communication_window=2, num_epoch=1, backend="ps",
                    ps_transport="socket",
                    ps_directory=f"{dsrv.host}:{dsrv.port}")
        t.train(ds, shuffle=False)
        assert ps.num_updates == t.resilience_stats_["logical_commits"] > 0
    finally:
        dsrv.stop()
        ps.stop()


@pytest.mark.parametrize("cls_name", ["ADAG", "DOWNPOUR"])
def test_chaos_kill_shard_and_directory_primary(cls_name, tmp_path):
    """THE acceptance (ISSUE 15): kill PS shard 1 AND the directory
    primary mid-run, with a mid-run elastic joiner whose whole sharded
    client is minted from a directory lookup (no endpoint constructor
    args anywhere in the worker path). The run completes exactly-once
    per shard, both failovers are real, and the post-failover center is
    bit-identical to the durable no-fault oracle (per-shard WAL
    replay)."""
    import jax

    import distkeras_tpu as dk
    from distkeras_tpu.resilience.wal import recover_ps_state
    from distkeras_tpu.sharding.ring import ShardPlan

    cls = getattr(dk, cls_name)
    wal = str(tmp_path / "wal")
    plan = FaultPlan(seed=3, drop_recv=0.01, max_faults=10,
                     kill_ps_after_commits=8, kill_shard_id=1,
                     kill_directory_after_ops=25,
                     join_worker_at_window={0: 2})
    t = cls(model_spec(), loss="sparse_softmax_cross_entropy",
            worker_optimizer="sgd", learning_rate=0.05, num_workers=2,
            batch_size=16, communication_window=2, num_epoch=2,
            backend="ps", ps_transport="socket", ps_num_shards=2,
            ps_chain_length=2, ps_wal_dir=wal, ps_failover_timeout=0.5,
            heartbeat_interval=0.1, elastic=True, directory=True,
            fault_plan=plan,
            retry_policy=RetryPolicy(max_attempts=200, base_delay=0.005,
                                     max_delay=0.2, deadline=120))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")   # both failover warnings expected
        with plan:
            t.train(blobs_dataset(n=768), shuffle=True)

    fs = plan.stats()
    assert fs["ps_kills"] == 1 and fs["directory_kills"] == 1
    assert fs["joins"] == 1
    rs = t.resilience_stats_
    # (a) both failovers really ran: the shard's chain promoted AND the
    # directory's standby took over
    assert rs["ps_failover"]["failovers"] >= 1
    assert rs["directory"]["failover"]["failovers"] >= 1
    # (b) exactly-once per shard across both kills + the live join
    s = t.ps_stats_
    assert s["num_updates"] == s["num_updates_max"] \
        == rs["logical_commits"]
    assert rs["elastic"]["assigner"]["exactly_once"]
    assert rs["elastic"]["joined"] == 1
    # (c) the joiner (like every worker) was minted from the directory:
    # lookups flowed through the surviving replica
    looked = (rs["directory"]["primary"]["lookups"]
              + fs["directory_ops"])
    assert looked > 0
    # (d) the post-failover center is bit-identical to the durable
    # oracle: each shard's ACTIVE log replays to exactly its final
    # sub-center (the repo's no-fault-oracle contract — the state a
    # never-crashed server holds after the same fold sequence)
    spec = model_spec()
    params, _ = t.spec.init_np(t.seed)
    sp = ShardPlan(params, 2)
    rule = t.allocate_merge_rule()
    per = rs["ps_failover"]["per_shard"]
    parts = []
    for sid in range(2):
        d = os.path.join(wal, f"shard-{sid:02d}")
        if per[sid]["failovers"] \
                and per[sid]["failover_log"][0]["via"] == "standby":
            d = os.path.join(d, "chain-1")
        # replay with the server's CONFIGURED worker count (the fold
        # scale ADAG uses), not the elastically-grown pool
        st = recover_ps_state(d, rule, t.num_workers, None,
                              template=sp.shard_template(params, sid))
        assert st is not None, d
        parts.append(st["center"])
    replayed = sp.join(parts)
    for a, b in zip(jax.tree.leaves(replayed),
                    jax.tree.leaves(t.trained_params_)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # (e) the whole root — shard logs, chain logs, directory logs —
    # verifies as one aggregate report naming the directory dirs
    rep = walmod.verify_tree(wal)
    assert rep["ok"], rep
    assert rep["num_directory_dirs"] >= 1
    # (f) it still learned something through all of that
    assert final_loss(t) < 1.5


# -- the serving router ------------------------------------------------------


VOCAB, MAXLEN = 64, 64


@pytest.fixture(scope="module")
def lm():
    import jax.numpy as jnp

    from distkeras_tpu.models.lm import transformer_lm

    spec = transformer_lm(vocab=VOCAB, maxlen=MAXLEN, dim=32, heads=4,
                          depth=2, dtype=jnp.float32,
                          pos_embedding="rope", kv_heads=2)
    params, _ = spec.init_np(0)
    return spec, params


def _serve_replica(spec, params, directory_seeds, key):
    from distkeras_tpu.serving.scheduler import GenerationEngine
    from distkeras_tpu.serving.server import GenerationServer

    eng = GenerationEngine(spec, params, max_batch=4, block_size=8,
                           max_queue=32)
    srv = GenerationServer(eng, poll_interval=0.02)
    srv.start()
    srv.register_with(directory_seeds, key=key, ttl=1.0)
    return srv


def _hard_kill(srv):
    """Tear a GenerationServer like a process kill: listener and every
    live connection gone mid-stream (no drain)."""
    srv._dir_stop.set()       # a corpse renews nothing
    srv._running = False
    srv.engine.stop(drain=False, timeout=2)
    try:
        srv._server_sock.close()
    except OSError:
        pass
    with srv._conns_lock:
        conns = list(srv._conns)
    for c in conns:
        try:
            c.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            c.close()
        except OSError:
            pass


def test_router_prefix_affinity_and_replica_kill(lm):
    """The router acceptance: ≥8 concurrent clients over 2 replicas —
    same-prefix requests land on the same replica (prefix-hash
    affinity), traffic spreads across both, one replica is killed
    mid-stream, and EVERY surviving stream completes (greedy streams
    matching the dense oracle — the replayed request is bit-identical
    to an unrouted one)."""
    from distkeras_tpu.models.lm import generate

    spec, params = lm
    dsrv = _start(DirectoryServer(default_ttl=None))
    seeds = [(dsrv.host, dsrv.port)]
    a = _serve_replica(spec, params, seeds, "a")
    b = _serve_replica(spec, params, seeds, "b")
    router = RoutedGenerationClient(directory=seeds, prefix_tokens=4,
                                    cooldown=0.5)
    try:
        assert set(router.replicas) == {"a", "b"}
        rng = np.random.default_rng(0)
        prefixes = [rng.integers(0, VOCAB, (4,)).astype(np.int32)
                    for _ in range(6)]
        # warm sequential pass: affinity — repeats of ONE prefix (with
        # different tails) land on exactly one replica
        for _ in range(2):
            router.generate(np.concatenate([
                prefixes[0], rng.integers(0, VOCAB, (3,)).astype(np.int32),
            ]), max_new_tokens=2)
        before = dict(router.stats()["routed"])
        for _ in range(3):
            router.generate(np.concatenate([
                prefixes[0], rng.integers(0, VOCAB, (3,)).astype(np.int32),
            ]), max_new_tokens=2)
        after = router.stats()["routed"]
        moved = {k: after.get(k, 0) - before.get(k, 0) for k in after}
        assert sum(1 for v in moved.values() if v) == 1, moved
        # distinct prefixes spread: both replicas see traffic
        for p in prefixes:
            router.generate(p, max_new_tokens=2)
        spread = router.stats()["routed"]
        assert all(spread.get(k, 0) > 0 for k in ("a", "b")), spread

        # ≥8 concurrent clients; one replica killed mid-stream
        results: dict[int, np.ndarray] = {}
        errs: dict[int, BaseException] = {}
        prompts = []

        def go(i, prompt):
            try:
                results[i] = router.generate(prompt, max_new_tokens=12)
            except BaseException as e:  # noqa: BLE001 — asserted empty
                errs[i] = e

        threads = []
        for i in range(10):
            p = np.concatenate([
                prefixes[i % len(prefixes)],
                rng.integers(0, VOCAB, (5,)).astype(np.int32),
            ])
            prompts.append(p)
            th = threading.Thread(target=go, args=(i, p))
            th.start()
            threads.append(th)
        time.sleep(0.05)          # let streams get in flight
        _hard_kill(a)
        for th in threads:
            th.join(timeout=90)
        assert not errs, errs
        assert len(results) == 10
        assert router.stats()["failovers"] >= 1
        # the replayed greedy streams match the dense oracle
        for i in (0, 5):
            oracle = generate(spec, params, prompts[i][None],
                              12)[0, len(prompts[i]):]
            np.testing.assert_array_equal(
                results[i], oracle[: len(results[i])]
            )
        # the killed replica DRAINS from discovery: its lease (1.0 s,
        # renewed at a third) lapses within 3× the TTL even under suite
        # load, and a refresh then routes around the corpse entirely
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if all(e["key"] != "a" for e in
                   DirectoryClient(seeds).lookup("serve")):
                break
            time.sleep(0.1)
        router.refresh(force=True)
        assert set(router.replicas) == {"b"}
    finally:
        router.close()
        _hard_kill(b)
        dsrv.stop()


def test_serving_register_with_withdraws_on_stop(lm):
    spec, params = lm
    dsrv = _start(DirectoryServer(default_ttl=None))
    try:
        srv = _serve_replica(spec, params, [(dsrv.host, dsrv.port)], "r")
        c = DirectoryClient([(dsrv.host, dsrv.port)])
        assert [e["key"] for e in c.lookup("serve")] == ["r"]
        srv.stop()
        assert c.lookup("serve") == []    # clean stop withdraws
        c.close()
    finally:
        dsrv.stop()


# -- shm rendezvous ----------------------------------------------------------


def test_shm_rendezvous_registers_and_withdraws_segments():
    """ROADMAP item 5 residual: dkshm segments minted while a directory
    rendezvous is installed are discoverable by name through the
    directory (so separate trainer processes on one host can share the
    lane), and every unlink withdraws — the process registry stays the
    no-directory fallback."""
    from distkeras_tpu import shm as shmmod
    from distkeras_tpu.directory import install_shm_rendezvous

    dsrv = _start(DirectoryServer(default_ttl=None))
    dc = DirectoryClient([(dsrv.host, dsrv.port)])
    uninstall = install_shm_rendezvous(dc)
    seg = None
    try:
        seg = shmmod.mint_segment("dkshm_rdvtest", 4096)
        names = [e["key"] for e in dc.shm_segments()]
        assert seg.name in names
        assert dc.lookup("shm", seg.name)[0]["meta"]["bytes"] == seg.size
        seg.close()
        seg.unlink()
        shmmod.unregister_segment(seg.name)
        assert dc.shm_segments() == []
        seg = None
    finally:
        if seg is not None:
            try:
                seg.close()
                seg.unlink()
                shmmod.unregister_segment(seg.name)
            except Exception:
                pass
        uninstall()
        # the fallback path is untouched after uninstall
        assert shmmod._RENDEZVOUS is None
        dc.close()
        dsrv.stop()
